"""Execution-engine seam: dispatch policy + sync points.

The reference's dependency engine (``src/engine/threaded_engine*.cc``
[unverified]) sequenced asynchronous op closures by read/write variable
dependencies across device worker threads. On TPU, XLA's asynchronous dispatch
plays that role natively: every jax op call enqueues device work and returns a
future-like ``jax.Array``; data dependencies *are* the value graph, so
RAW/WAR/WAW ordering is by construction and the race class the ThreadedEngine
guarded against does not exist (SURVEY.md section 5).

What survives is the *policy seam*:

- ``MXNET_ENGINE_TYPE=NaiveEngine`` selects synchronous execution (each op
  blocks until its results are ready) — the reference's de-facto debugging
  mode for bisecting async issues. ``ThreadedEngine`` /
  ``ThreadedEnginePerDevice`` (the default) mean "let XLA dispatch async".
- ``wait_for_var`` / ``wait_for_all`` are the explicit sync points
  (reference: ``Engine::WaitForVar`` / ``WaitForAll``).
- A bulk-execution hint mirrors ``MXNET_GLUON_EXEC_BULK_SIZE`` but is advisory:
  under ``hybridize()`` the whole graph is one XLA executable, which is the
  limit case of bulking.
"""

from __future__ import annotations

import contextlib
from typing import Iterable

import jax

from .base import env_str

__all__ = [
    "Engine",
    "engine",
    "is_async",
    "wait_for_all",
    "bulk",
    "set_bulk_size",
]


class Engine:
    """Dispatch policy singleton (reference: ``Engine::Get()``)."""

    def __init__(self):
        kind = env_str("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")
        self._async = kind not in ("NaiveEngine", "naive", "sync")
        self._bulk_size = 0
        self._live_arrays = 0  # informational

    @property
    def kind(self) -> str:
        return "ThreadedEnginePerDevice" if self._async else "NaiveEngine"

    def set_async(self, flag: bool):
        self._async = bool(flag)

    def is_async(self) -> bool:
        return self._async

    def on_outputs(self, arrays: Iterable[jax.Array]):
        """Post-dispatch hook: in naive mode, block until results are ready."""
        if not self._async:
            for a in arrays:
                if hasattr(a, "block_until_ready"):
                    a.block_until_ready()

    # -- sync points --------------------------------------------------------
    @staticmethod
    def wait_for_var(array):
        if hasattr(array, "block_until_ready"):
            array.block_until_ready()

    @staticmethod
    def wait_for_all():
        """Reference: ``Engine::WaitForAll`` — barrier on all pending work."""
        try:
            jax.effects_barrier()
        except Exception:  # pragma: no cover - older jax fallback
            pass
        for dev in jax.devices():
            # synchronize per device; jax has no public global barrier, so
            # run a trivial computation and block on it.
            jax.device_put(0, dev).block_until_ready()


_ENGINE = None


def engine() -> Engine:
    global _ENGINE
    if _ENGINE is None:
        _ENGINE = Engine()
    return _ENGINE


def is_async() -> bool:
    return engine().is_async()


def wait_for_all():
    engine().wait_for_all()


def set_bulk_size(size: int) -> int:
    """Advisory (reference: ``MXEngineSetBulkSize``). Returns previous value."""
    eng = engine()
    prev, eng._bulk_size = eng._bulk_size, int(size)
    return prev


@contextlib.contextmanager
def bulk(size: int):
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
