"""mxnet_tpu: a TPU-native deep-learning framework with MXNet's capabilities.

Brand-new implementation targeting TPU (JAX/XLA/Pallas/pjit) with the API
surface of the reference (``ZheyuYe/incubator-mxnet``, an apache/mxnet fork —
see SURVEY.md at the repo root for the structural analysis and provenance).
Not a port: no dependency engine (XLA async dispatch), no nnvm dual IR
(``hybridize()`` stages through ``jax.jit``), no ps-lite/NCCL transport
(mesh + GSPMD collectives over ICI/DCN).

Conventional entry point::

    import mxnet_tpu as mx
    x = mx.nd.ones((2, 3), ctx=mx.tpu())
"""

from __future__ import annotations

__version__ = "0.1.0"

import os as _os

import jax as _jax

# TPU-native PRNG default: threefry key derivation burns measurable step
# time in vector ops on TPU (profiled ~6ms/step of a 40ms BERT step just
# for dropout masks); rbg uses the hardware RNG path and is the accepted
# accelerator default. Semantics (splittable, deterministic per seed) are
# unchanged — only the stream values differ. This is process-global and
# affects co-resident jax code; set MXNET_TPU_PRNG=threefry (or any other
# jax impl name, or "default") to opt out before import.
_prng = _os.environ.get("MXNET_TPU_PRNG", "rbg")
if _prng != "default":
    try:
        _jax.config.update("jax_default_prng_impl", _prng)
    except Exception:  # pragma: no cover - ancient jax without the flag
        pass

from . import base
from .base import MXNetError
from . import context
from .context import Context, cpu, cpu_pinned, gpu, tpu, current_context, num_gpus, num_tpus
from . import engine
from . import random
from . import ndarray
from . import ndarray as nd
from .ndarray.ndarray import NDArray, waitall
from . import numpy as np  # noqa: F401 - mx.np
from . import numpy_extension as npx  # noqa: F401 - mx.npx
from . import autograd
from . import imperative
from . import util
from .util import is_np_array, is_np_shape, set_np, reset_np

# Higher layers (grown incrementally; see SURVEY.md section 7 build order).
# Each import is optional only until its module lands this round.
import importlib as _importlib

for _mod, _aliases in [
    ("initializer", ()),
    ("optimizer", ()),
    ("metric", ()),
    ("symbol", ("sym",)),
    ("executor", ()),
    ("gluon", ()),
    ("module", ()),
    ("kvstore", ("kv",)),
    ("parallel", ()),
    ("serving", ()),
    ("recordio", ()),
    ("io", ()),
    ("image", ()),
    ("telemetry", ()),
    ("compile_cache", ()),
    ("profiler", ()),
    ("amp", ()),
    ("runtime", ()),
    ("test_utils", ()),
    ("checkpoint", ()),
    ("callback", ()),
    ("library", ()),
    ("operator", ()),
    ("contrib", ()),
    ("onnx", ()),
    ("debug", ()),
]:
    try:
        _m = _importlib.import_module(f".{_mod}", __name__)
    except ModuleNotFoundError as _e:
        # tolerate only "module not written yet" — real import bugs surface
        if _e.name != f"{__name__}.{_mod}":
            raise
        continue
    globals()[_mod] = _m
    for _a in _aliases:
        globals()[_a] = _m

if "initializer" in globals():
    init = initializer.init  # mx.init alias namespace
if "optimizer" in globals():
    lr_scheduler = optimizer.lr_scheduler
if "compile_cache" in globals():
    # persistent XLA compilation cache: default-on under the convention
    # dir; MXTPU_COMPILE_CACHE_DIR pins/paranoid-persists/disables
    compile_cache.setup()
