"""``mx.npx``: operator extensions beyond the NumPy standard.

Reference: ``python/mxnet/numpy_extension/`` [unverified] — neural-net ops
(softmax, batch_norm, convolution, embedding...) exposed with numpy-semantics
arrays. Wraps the same op registry as ``mx.nd``.
"""

from __future__ import annotations

import sys

from ..ndarray import register as _register
from ..util import (  # noqa: F401 - API parity
    is_np_array,
    is_np_shape,
    reset_np,
    set_np,
    use_np,
    use_np_array,
    use_np_shape,
)

# npx exposes the nn/contrib op surface with numpy arrays; the registry is
# shared, so just install every op here too.
_register.populate_module(sys.modules[__name__], namespace="nd")

from ..context import cpu, current_context, gpu, num_gpus, tpu  # noqa: F401, E402


def seed(s):
    from ..random import seed as _seed

    _seed(s)
