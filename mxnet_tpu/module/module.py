"""Module: symbol-based training loop (reference:
``python/mxnet/module/module.py``, ``base_module.py`` [unverified]).

The reference's ``DataParallelExecutorGroup`` (one executor per GPU, split
batches) is NOT replicated: one Executor backed by a jitted program covers a
chip, and multi-device data parallelism is a sharding of that program
(SURVEY.md §2.3) — the Module API surface stays the same."""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..ndarray import array as nd_array
from .. import initializer as _init
from .. import metric as _metric
from .. import optimizer as _opt

__all__ = ["Module", "BucketingModule"]


class Module:
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):
        self._symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._fixed_param_names = list(fixed_param_names or [])
        self._exec = None
        self._optimizer = None
        self._updater = None
        self._kvstore = None
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._logger = logger
        # multi-device data parallelism: the reference's
        # DataParallelExecutorGroup replicated one executor per GPU and
        # host-split batches; here a context LIST becomes a 1-d device mesh
        # and batches are sharded over it — GSPMD partitions the ONE jitted
        # executor program (grad psum inserted automatically)
        self._context = list(context) if isinstance(
            context, (list, tuple)
        ) else ([context] if context is not None else None)
        self._data_sharding = None

    # ------------------------------------------------------------ properties
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    def _param_names(self):
        return [
            n for n in self._symbol.list_arguments()
            if n not in self._data_names and n not in self._label_names
        ]

    # ----------------------------------------------------------------- bind
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        shapes = {}
        for desc in data_shapes:
            name, shape = desc[0], desc[1]
            shapes[name] = tuple(shape)
        if label_shapes:
            for desc in label_shapes:
                shapes[desc[0]] = tuple(desc[1])
        self._exec = self._symbol.simple_bind(
            grad_req=grad_req if for_training else "null", **shapes
        )
        if self._context and len(self._context) > 1:
            import numpy as _onp

            from jax.sharding import Mesh, NamedSharding, PartitionSpec

            devs = [c.jax_device() for c in self._context]
            mesh = Mesh(_onp.array(devs), ("data",))
            self._data_sharding = NamedSharding(mesh, PartitionSpec("data"))
        self._for_training = for_training
        self.binded = True

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        if not self.binded:
            raise MXNetError("call bind before init_params")
        if initializer is None:
            initializer = _init.Uniform(0.01)
        for name in self._param_names():
            arr = self._exec.arg_dict[name]
            if arg_params and name in arg_params:
                src = arg_params[name]
                arr._rebind(
                    src.data if isinstance(src, NDArray) else jnp.asarray(src)
                )
            else:
                initializer(_init.InitDesc(name), arr)
        if aux_params:
            # trained BN moving stats etc. (reference: set_params copies
            # aux states into the executor alongside args)
            self._exec.copy_params_from({}, aux_params,
                                        allow_extra_params=allow_extra)
        self.params_initialized = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, str):
            optimizer = _opt.create(optimizer, **dict(optimizer_params))
        self._optimizer = optimizer
        self._updater = _opt.get_updater(optimizer)
        self.optimizer_initialized = True

    # -------------------------------------------------------------- compute
    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = self._for_training
        feed = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feed[name] = self._shard(arr)
        if data_batch.label:
            for name, arr in zip(self._label_names, data_batch.label):
                feed[name] = self._shard(arr)
        self._exec.forward(is_train=is_train, **feed)

    def _shard(self, arr):
        """Split a batch over the context mesh (DataParallelExecutorGroup
        role); no-op for a single context."""
        if self._data_sharding is None:
            return arr
        import jax

        data = arr.data if isinstance(arr, NDArray) else jnp.asarray(arr)
        n = len(self._context)
        if data.shape[0] % n:
            raise MXNetError(
                f"batch size {data.shape[0]} is not divisible by the "
                f"{n} contexts; pick a batch size that splits evenly "
                "(NDArrayIter pads the final batch to batch_size)"
            )
        return NDArray(jax.device_put(data, self._data_sharding))

    def backward(self, out_grads=None):
        self._exec.backward(out_grads)

    def update(self):
        if not self.optimizer_initialized:
            raise MXNetError("call init_optimizer before update")
        for i, name in enumerate(self._param_names()):
            grad = self._exec.grad_dict.get(name)
            if grad is None or name in self._fixed_param_names:
                continue
            self._updater(i, grad, self._exec.arg_dict[name])

    def get_outputs(self, merge_multi_context=True):
        return self._exec.outputs

    def get_params(self):
        args = {n: self._exec.arg_dict[n] for n in self._param_names()}
        return args, dict(self._exec.aux_dict)

    def set_params(self, arg_params, aux_params=None, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init,
                         allow_extra=allow_extra)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        eval_metric.update(labels, self._exec.outputs)

    # ------------------------------------------------------------------ fit
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None):
        assert num_epoch is not None, "num_epoch required for fit"
        if not self.binded:
            self.bind(
                data_shapes=train_data.provide_data,
                label_shapes=train_data.provide_label,
                for_training=True, force_rebind=force_rebind,
            )
        self.init_params(initializer, arg_params, aux_params, allow_missing,
                         force_init)
        self.init_optimizer(kvstore, optimizer, optimizer_params)
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)

        for epoch in range(begin_epoch, num_epoch):
            eval_metric.reset()
            train_data.reset()
            for nbatch, batch in enumerate(train_data):
                self.forward(batch, is_train=True)
                self.backward()
                self.update()
                self.update_metric(eval_metric, batch.label)
                if batch_end_callback is not None:
                    for cb in _as_list(batch_end_callback):
                        cb(_BatchEndParam(epoch, nbatch, eval_metric))
            name_val = eval_metric.get_name_value()
            for name, val in name_val:
                self._logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            if epoch_end_callback is not None:
                arg_params, aux_params = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self._symbol, arg_params, aux_params)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric or eval_metric)
                for name, val in res:
                    self._logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                      name, val)

    def score(self, eval_data, eval_metric, num_batch=None, **kwargs):
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        eval_metric.reset()
        eval_data.reset()
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            self.update_metric(eval_metric, batch.label)
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                always_output_list=False):
        outputs = []
        eval_data.reset()
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            outputs.append([o.asnumpy() for o in self._exec.outputs])
        if merge_batches:
            merged = [
                nd_array(_np.concatenate([o[i] for o in outputs]))
                for i in range(len(outputs[0]))
            ]
            return merged[0] if len(merged) == 1 and not always_output_list \
                else merged
        return outputs

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        self._symbol.save(f"{prefix}-symbol.json")
        from ..ndarray import save as nd_save

        args, aux = self.get_params()
        save_dict = {f"arg:{k}": v for k, v in args.items()}
        save_dict.update({f"aux:{k}": v for k, v in aux.items()})
        nd_save(f"{prefix}-{epoch:04d}.params", save_dict)

    @staticmethod
    def load_checkpoint(prefix, epoch):
        from .. import symbol as sym_mod
        from ..ndarray import load as nd_load

        symbol = sym_mod.load(f"{prefix}-symbol.json")
        loaded = nd_load(f"{prefix}-{epoch:04d}.params")
        arg_params = {
            k[4:]: v for k, v in loaded.items() if k.startswith("arg:")
        }
        aux_params = {
            k[4:]: v for k, v in loaded.items() if k.startswith("aux:")
        }
        return symbol, arg_params, aux_params

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        symbol, arg_params, aux_params = Module.load_checkpoint(prefix, epoch)
        mod = Module(symbol, **kwargs)
        mod._preloaded = (arg_params, aux_params)
        return mod


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """Module-level checkpoint writer (reference:
    ``mx.model.save_checkpoint``); used by ``callback.do_checkpoint``."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    from ..ndarray import save as nd_save

    save_dict = {f"arg:{k}": v for k, v in (arg_params or {}).items()}
    save_dict.update({f"aux:{k}": v for k, v in (aux_params or {}).items()})
    nd_save(f"{prefix}-{epoch:04d}.params", save_dict)


class _BatchEndParam:
    def __init__(self, epoch, nbatch, eval_metric):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = None


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


class BucketingModule:
    """Variable-length sequence training (reference: ``BucketingModule``).

    One Module per bucket key; XLA's per-shape compile cache plays the role
    the per-bucket executor pool played in the reference."""

    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, **kwargs):
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._modules: Dict = {}
        self._kwargs = kwargs
        self._curr_module = None
        self.binded = False
        self.params_initialized = False

    def _get_module(self, bucket_key):
        if bucket_key not in self._modules:
            symbol, data_names, label_names = self._sym_gen(bucket_key)
            self._modules[bucket_key] = Module(
                symbol, data_names, label_names, **self._kwargs
            )
        return self._modules[bucket_key]

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             **kwargs):
        mod = self._get_module(self._default_bucket_key)
        mod.bind(data_shapes, label_shapes, for_training, **kwargs)
        self._curr_module = mod
        self.binded = True

    def init_params(self, *args, **kwargs):
        self._curr_module.init_params(*args, **kwargs)
        self.params_initialized = True

    def init_optimizer(self, *args, **kwargs):
        self._curr_module.init_optimizer(*args, **kwargs)

    def forward(self, data_batch, is_train=None):
        key = getattr(data_batch, "bucket_key", self._default_bucket_key)
        mod = self._get_module(key)
        if not mod.binded:
            mod.bind(
                [(n, a.shape) for n, a in zip(
                    self._curr_module._data_names, data_batch.data)],
                [(n, a.shape) for n, a in zip(
                    self._curr_module._label_names, data_batch.label or [])]
                or None,
                for_training=True,
            )
            # share weights with the default-bucket module: same NDArray
            # objects, so updates through any bucket are visible to all
            for n in mod._param_names():
                if n in self._curr_module._exec.arg_dict:
                    mod._exec.arg_dict[n] = self._curr_module._exec.arg_dict[n]
            mod.params_initialized = True
            mod._optimizer = self._curr_module._optimizer
            mod._updater = self._curr_module._updater
            mod.optimizer_initialized = True
        self._switched = mod
        mod.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        self._switched.backward(out_grads)

    def update(self):
        self._switched.update()

    def get_outputs(self, merge_multi_context=True):
        return self._switched.get_outputs(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._switched.update_metric(eval_metric, labels)
