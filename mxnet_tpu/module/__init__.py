"""Legacy Module API (reference: ``python/mxnet/module/`` [unverified])."""

from .module import Module, BucketingModule

__all__ = ["Module", "BucketingModule"]
