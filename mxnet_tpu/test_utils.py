"""Test utilities (reference: ``python/mxnet/test_utils.py`` [unverified]).

The reference's testing leverage (SURVEY.md §4): NumPy reference impls +
finite-difference gradient checks + cross-context consistency. All three are
here: ``check_numeric_gradient`` (central differences vs autograd),
``check_consistency`` (re-run across contexts/dtypes), dtype-aware
``assert_almost_equal``.
"""

from __future__ import annotations

import functools
import random as _pyrandom
from typing import Callable, Dict, List, Optional, Sequence

import numpy as _np

from .base import MXNetError
from .context import Context, cpu, current_context
from .ndarray.ndarray import NDArray
from .ndarray import array as nd_array
from . import autograd
from . import random as _random

__all__ = [
    "default_context",
    "default_dtype",
    "get_atol",
    "get_rtol",
    "rand_ndarray",
    "rand_shape_2d",
    "rand_shape_3d",
    "rand_shape_nd",
    "assert_almost_equal",
    "almost_equal",
    "same",
    "check_numeric_gradient",
    "check_symbolic_forward",
    "numeric_grad",
    "check_consistency",
    "with_seed",
    "assert_exception",
]

_DEFAULT_RTOL = {
    _np.dtype(_np.float16): 1e-2,
    _np.dtype(_np.float32): 1e-4,
    _np.dtype(_np.float64): 1e-5,
    _np.dtype(_np.int32): 0,
    _np.dtype(_np.int64): 0,
}
_DEFAULT_ATOL = {
    _np.dtype(_np.float16): 1e-1,
    _np.dtype(_np.float32): 1e-3,
    _np.dtype(_np.float64): 1e-20,
    _np.dtype(_np.int32): 0,
    _np.dtype(_np.int64): 0,
}


def default_context():
    return current_context()


def default_dtype():
    return _np.float32


def get_rtol(rtol=None):
    return _DEFAULT_RTOL[_np.dtype(_np.float32)] if rtol is None else rtol


def get_atol(atol=None):
    return _DEFAULT_ATOL[_np.dtype(_np.float32)] if atol is None else atol


def rand_shape_2d(dim0=10, dim1=10):
    return (_pyrandom.randint(1, dim0), _pyrandom.randint(1, dim1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (
        _pyrandom.randint(1, dim0),
        _pyrandom.randint(1, dim1),
        _pyrandom.randint(1, dim2),
    )


def rand_shape_nd(num_dim, dim=10):
    return tuple(_np.random.randint(1, dim + 1, size=num_dim))


def rand_ndarray(shape, stype="default", density=None, dtype=None,
                 distribution="uniform"):
    """Random array incl. sparse stypes (reference: test_utils.rand_ndarray;
    sparse here is the dense-backed facade with real sparsity pattern)."""
    if distribution == "uniform":
        data = _np.random.uniform(-1, 1, size=shape)
    elif distribution == "normal":
        data = _np.random.normal(size=shape)
    elif distribution == "powerlaw":
        data = _np.random.pareto(2.0, size=shape)
    else:
        raise MXNetError(f"unknown distribution {distribution}")
    data = data.astype(dtype or "float32")
    if stype == "default":
        return nd_array(data)
    density = 0.5 if density is None else float(density)
    if stype == "row_sparse":
        from .ndarray.sparse import RowSparseNDArray

        keep = _np.random.uniform(size=shape[0]) < density
        data[~keep] = 0
        return RowSparseNDArray(_jnp_asarray(data))
    if stype == "csr":
        from .ndarray.sparse import CSRNDArray

        mask = _np.random.uniform(size=shape) < density
        data = data * mask
        return CSRNDArray(_jnp_asarray(data))
    raise MXNetError(f"unknown stype {stype}")


def _jnp_asarray(a):
    import jax.numpy as jnp

    return jnp.asarray(a)


def same(a, b):
    return _np.array_equal(_as_np(a), _as_np(b))


def _as_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    return _np.allclose(
        _as_np(a), _as_np(b), rtol=get_rtol(rtol), atol=get_atol(atol),
        equal_nan=equal_nan,
    )


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    a_np, b_np = _as_np(a), _as_np(b)
    rtol, atol = get_rtol(rtol), get_atol(atol)
    if not _np.allclose(a_np, b_np, rtol=rtol, atol=atol, equal_nan=equal_nan):
        idx = _np.unravel_index(
            _np.argmax(_np.abs(a_np - b_np)), a_np.shape
        ) if a_np.shape else ()
        raise AssertionError(
            f"{names[0]} and {names[1]} differ beyond rtol={rtol} atol={atol}:"
            f" max abs err {float(_np.max(_np.abs(a_np - b_np))):.3e} at {idx};"
            f" {names[0]}={a_np[idx] if a_np.shape else a_np}"
            f" {names[1]}={b_np[idx] if b_np.shape else b_np}"
        )


def numeric_grad(f: Callable, inputs: List[_np.ndarray], eps=1e-4):
    """Central finite differences of scalar-valued f wrt each input array."""
    grads = []
    for i, x in enumerate(inputs):
        g = _np.zeros_like(x, dtype=_np.float64)
        flat = x.reshape(-1)
        gflat = g.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = float(f(*inputs))
            flat[j] = orig - eps
            fm = float(f(*inputs))
            flat[j] = orig
            gflat[j] = (fp - fm) / (2 * eps)
        grads.append(g)
    return grads


def _probe_rig_staleness(scalar_f, host_inputs, eps) -> bool:
    """True only when the transfer rig serves STALE results for in-place
    host-buffer mutation (the tunneled-TPU failure mode), never for a
    merely flat function.

    For EVERY input (not just the first — the first may be an index/mask
    arg the output legitimately ignores): perturb the largest-magnitude
    elements in place — ``numeric_grad``'s exact access pattern, the one
    the tunnel serves stale — and re-evaluate. If the output never
    moves, re-run the same perturbations through FRESHLY allocated
    buffers (one per evaluation — a fresh allocation forces a genuine
    transfer). Fresh-buffer movement with in-place flatness is the
    staleness signature -> skip. Flat both ways is a genuinely flat
    function (sign/round/STE, or an op ignoring its input): keep going
    so the finite-difference comparison fails or passes honestly."""
    base = float(scalar_f(*host_inputs))
    delta = 4.0 * eps
    for ai, arr in enumerate(host_inputs):
        if not arr.size:
            continue
        flat = arr.reshape(-1)
        idxs = _np.argsort(-_np.abs(flat))[:3]
        moved = False
        for j in idxs:
            orig = flat[j]
            flat[j] = orig + delta
            up = float(scalar_f(*host_inputs))
            flat[j] = orig - delta
            dn = float(scalar_f(*host_inputs))
            flat[j] = orig
            # NaN counts as movement: let the real comparison surface
            # it rather than mask it as rig staleness
            if not (up == base and dn == base):
                moved = True
                break
        if moved:
            continue  # this input demonstrably reaches the output
        for j in idxs:
            orig = float(flat[j])
            for sign in (1.0, -1.0):
                fresh = arr.copy()  # fresh buffer per eval: real transfer
                fresh.reshape(-1)[j] = orig + sign * delta
                probe_inputs = list(host_inputs)
                probe_inputs[ai] = fresh
                if float(scalar_f(*probe_inputs)) != base:
                    return True  # fresh moved, in-place did not: stale rig
        # flat both ways: genuinely flat w.r.t. this input — probe the rest
    return False


def check_numeric_gradient(fn: Callable, inputs: Sequence, eps=1e-3,
                           rtol=1e-2, atol=1e-3):
    """Compare autograd gradients of ``sum(fn(*inputs))`` against central
    finite differences (reference: ``check_numeric_gradient``).

    ``fn`` maps NDArrays -> NDArray.
    """
    nds = [
        x if isinstance(x, NDArray) else nd_array(_np.asarray(x, "float64"))
        for x in inputs
    ]
    for x in nds:
        x.attach_grad()
    with autograd.record():
        out = fn(*nds)
        loss = out.sum()
    loss.backward()
    analytic = [x.grad.asnumpy() for x in nds]

    def scalar_f(*np_inputs):
        outs = fn(*[nd_array(a) for a in np_inputs])
        return outs.sum().asscalar()

    host_inputs = [x.asnumpy().astype(_np.float64) for x in nds]

    import jax

    if jax.default_backend() != "cpu":
        # STALENESS PROBE (round 5, tightened ADVICE r5): the tunneled
        # TPU backend sometimes returns results for a PREVIOUS transfer
        # of a same-shape host buffer (minimal pure-jax repro in
        # TESTING.md round 5 — not a framework bug; CPU runs are exact).
        # Finite differences are meaningless if perturbed inputs read
        # back stale, so detect that — and ONLY that: a locally flat fn
        # (sign/round/STE) or an input the output genuinely ignores must
        # not skip, or 'op ignores its input' becomes invisible on TPU.
        if _probe_rig_staleness(scalar_f, host_inputs, eps):
            import pytest

            pytest.skip(
                "tunneled backend returned stale transfers (probe: "
                "in-place-mutated inputs never changed the output, but "
                "the same perturbation through a freshly allocated host "
                "buffer did); numeric gradients are validated on the "
                "CPU suite")
    numeric = numeric_grad(scalar_f, host_inputs, eps=eps)
    for i, (a, n) in enumerate(zip(analytic, numeric)):
        assert_almost_equal(
            a, n, rtol=rtol, atol=atol, names=(f"analytic[{i}]", f"numeric[{i}]")
        )


def check_symbolic_forward(fn: Callable, inputs: Sequence,
                           expected: Sequence[_np.ndarray], rtol=None,
                           atol=None):
    """Run fn on NDArray inputs, compare each output against numpy expected."""
    nds = [x if isinstance(x, NDArray) else nd_array(x) for x in inputs]
    outs = fn(*nds)
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    for i, (o, e) in enumerate(zip(outs, expected)):
        assert_almost_equal(o, e, rtol, atol, names=(f"out[{i}]", f"expected[{i}]"))


def check_consistency(fn: Callable, inputs: Sequence, ctx_list=None,
                      dtypes=("float32",), rtol=None, atol=None):
    """Re-run fn across contexts/dtypes and compare results (reference:
    ``check_consistency`` CPU-vs-GPU; here CPU vs TPU vs dtype variants)."""
    baseline = None
    for dtype in dtypes:
        nds = [nd_array(_as_np(x).astype(dtype)) for x in inputs]
        out = _as_np(fn(*nds))
        if baseline is None:
            baseline = out
        else:
            assert_almost_equal(
                out.astype("float32"), baseline.astype("float32"),
                rtol=_DEFAULT_RTOL.get(_np.dtype(dtype), 1e-3),
                atol=_DEFAULT_ATOL.get(_np.dtype(dtype), 1e-2),
                names=(f"dtype:{dtype}", "baseline"),
            )
    return baseline


def with_seed(seed=None):
    """Decorator giving each test a reproducible seed (reference: @with_seed)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            s = seed if seed is not None else _np.random.randint(0, 2 ** 31)
            _np.random.seed(s)
            _pyrandom.seed(s)
            _random.seed(s)
            try:
                return fn(*args, **kwargs)
            except Exception:
                print(f"test failed with seed={s}")
                raise

        return wrapper

    return deco


def assert_exception(fn, exception_type, *args, **kwargs):
    try:
        fn(*args, **kwargs)
    except exception_type:
        return
    raise AssertionError(f"{fn} did not raise {exception_type}")
