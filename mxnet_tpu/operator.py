"""Pure-Python custom operators — the reference's ``mx.operator`` module
(``CustomOp``/``CustomOpProp``/``register`` over
``src/operator/custom/custom.cc`` [unverified]).

The reference dispatched these through C++->Python callbacks on the
engine's CPU stream; here the op body runs eagerly on host numpy (same
execution model) and hooks into the framework tape for autograd. Loaded
ops join the one operator registry, so ``mx.nd.Custom(data,
op_type='my_op')`` and direct ``mx.nd.my_op(data)`` both work.

The in/out protocol matches the reference:

    @mx.operator.register("sigmoid2x")
    class Sigmoid2xProp(mx.operator.CustomOpProp):
        def list_arguments(self): return ["data"]
        def list_outputs(self):   return ["output"]
        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []
        def create_operator(self, ctx, shapes, dtypes):
            return Sigmoid2x()

    class Sigmoid2x(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            y = 2 / (1 + np.exp(-in_data[0].asnumpy()))
            self.assign(out_data[0], req[0], mx.nd.array(y))
        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            y = out_data[0].asnumpy() / 2
            g = out_grad[0].asnumpy() * 2 * y * (1 - y)
            self.assign(in_grad[0], req[0], mx.nd.array(g))
"""

from __future__ import annotations

from typing import Dict, List, Type

import jax.numpy as jnp
import numpy as _np

from . import autograd as _ag
from .base import MXNetError
from .ndarray.ndarray import NDArray
from .ops import registry as _registry

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered"]

_PROPS: Dict[str, Type["CustomOpProp"]] = {}


class CustomOp:
    """Base class for the operator body (reference ``mx.operator.CustomOp``)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise MXNetError(
            f"{type(self).__name__} does not implement backward()"
        )

    @staticmethod
    def assign(dst: NDArray, req: str, src):
        """Write ``src`` into ``dst`` honoring grad_req semantics."""
        if req == "null":
            return
        src_nd = src if isinstance(src, NDArray) else NDArray(jnp.asarray(src))
        if req in ("write", "inplace"):
            dst._rebind(src_nd.data.astype(dst.data.dtype))
        elif req == "add":
            dst._rebind(dst.data + src_nd.data.astype(dst.data.dtype))
        else:
            raise MXNetError(f"unknown req {req!r}")


class CustomOpProp:
    """Operator metadata + factory (reference ``CustomOpProp``)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self) -> List[str]:
        return ["data"]

    def list_outputs(self) -> List[str]:
        return ["output"]

    def list_auxiliary_states(self) -> List[str]:
        return []

    def infer_shape(self, in_shape):
        """Default: single output shaped like input 0 (reference default)."""
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def create_operator(self, ctx, shapes, dtypes) -> CustomOp:
        raise NotImplementedError


def register(reg_name: str):
    """Class decorator registering a CustomOpProp under ``reg_name``.

    Exposes the op both as ``mx.nd.<reg_name>`` and through
    ``mx.nd.Custom(..., op_type=reg_name)``."""

    def deco(prop_cls: Type[CustomOpProp]):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register() needs a CustomOpProp subclass")
        if reg_name in _PROPS:
            raise MXNetError(f"custom op {reg_name!r} already registered")
        _PROPS[reg_name] = prop_cls

        def fn(*arrays, **kw):
            import jax

            if any(isinstance(a, jax.core.Tracer) for a in arrays):
                raise MXNetError(
                    f"python CustomOp {reg_name!r} runs eagerly only "
                    "(reference custom.cc semantics); call outside "
                    "jit/hybridize"
                )
            return _run_custom(reg_name, arrays, kw)

        fn.__name__ = reg_name
        fn.__doc__ = f"Python custom operator {reg_name!r} " \
            f"({prop_cls.__name__})."
        if _registry.maybe_get(reg_name) is None:
            # the fn makes its own tape entry; keep the invoke layer's
            # jax.vjp routing away from the python callback
            _registry.register(reg_name, differentiable=False,
                               self_recording=True)(fn)
            import sys

            from .ndarray import register as _nd_register

            _nd_register.populate_module(
                sys.modules["mxnet_tpu.ndarray"], "nd"
            )
        return prop_cls

    return deco


def get_all_registered():
    return sorted(_PROPS)


def _run_custom(reg_name, raw_inputs, kwargs):
    prop = _PROPS[reg_name](**kwargs) if kwargs else _PROPS[reg_name]()
    in_nds = [a if isinstance(a, NDArray) else NDArray(jnp.asarray(a))
              for a in raw_inputs]
    in_shapes = [tuple(a.shape) for a in in_nds]
    out_shapes, = [prop.infer_shape(list(in_shapes))[1]]
    in_types = [_np.dtype(str(a.data.dtype)) for a in in_nds]
    out_types = prop.infer_type(list(in_types))[1]
    body = prop.create_operator(None, in_shapes, in_types)
    out_nds = [NDArray(jnp.zeros(tuple(s), t))
               for s, t in zip(out_shapes, out_types)]

    recording = _ag.is_recording()
    with _ag.pause():
        body.forward(recording, ["write"] * len(out_nds), in_nds, out_nds,
                     [])
    if not recording:
        return out_nds[0] if len(out_nds) == 1 else tuple(out_nds)

    fwd_outs = [NDArray(o.data) for o in out_nds]

    class _Fn(_ag.Function):
        def forward(self, *ins):
            return tuple(fwd_outs) if len(fwd_outs) > 1 else fwd_outs[0]

        def backward(self, *ogs):
            in_grads = [NDArray(jnp.zeros_like(a.data)) for a in in_nds]
            body.backward(
                ["write"] * len(in_grads), list(ogs), in_nds, out_nds,
                in_grads, [],
            )
            return tuple(in_grads)

    return _Fn()(*in_nds)


def _custom_dispatch(*arrays, op_type=None, **kw):
    """``nd.Custom(data..., op_type='name')`` — the reference's generic
    entry point for python custom ops."""
    if op_type is None or op_type not in _PROPS:
        raise MXNetError(
            f"Custom: unknown op_type {op_type!r}; registered: "
            f"{get_all_registered()}"
        )
    return _run_custom(op_type, arrays, kw)


_registry.register("Custom", differentiable=False,
                   self_recording=True)(_custom_dispatch)
