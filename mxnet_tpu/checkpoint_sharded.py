"""Sharded (per-process) checkpoint layout for distributed arrays.

SURVEY.md §5's "tensorstore-style sharded arrays" plan: every process
writes exactly the shards it owns (no gather, no process ever holds a
full copy of a TP-sharded array), plus a JSON index describing where
each global slice lives; restore re-assembles arrays onto the CURRENT
mesh via ``jax.make_array_from_callback`` — resharding onto a different
mesh/process count is allowed, since the callback reads arbitrary
global slices from the saved pieces.

Reference analogue: Trainer.save_states / Module.save_checkpoint
(``python/mxnet/gluon/trainer.py`` [unverified]) persisted replicated
state from one process; the sharded layout here is the multi-host
extension those APIs never had.

Write protocol (commit-marker, crash-safe):
  {dir}/shards_p{K}.npz      one file per process, its replica-0 shards
  {dir}/index_p{K}.json      name -> [slice bounds, npz key] for that file
  {dir}/ckpt_meta.json       global shapes/dtypes + process_count (proc 0)
  {dir}/DONE.p{K}            per-process commit marker, written LAST
A checkpoint is committed iff DONE.p{k} exists for every k in
range(process_count). Assumes the directory is on a filesystem all
processes can read at restore time (the standard checkpoint contract).
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, Optional, Union

import jax
import numpy as _np

from . import telemetry as _tel
from .base import MXNetError

__all__ = ["save_sharded", "load_sharded", "is_committed", "commit_token",
           "latest_committed"]


def _norm_bounds(index, shape):
    """Normalize a per-device index (tuple of slices) to [[start, stop]]."""
    out = []
    for sl, dim in zip(index, shape):
        start, stop, step = sl.indices(dim)
        if step != 1:
            raise MXNetError("strided shards are not supported")
        out.append([int(start), int(stop)])
    return out


def _encode_spec(a) -> Optional[list]:
    """JSON-able PartitionSpec of a NamedSharded array (None otherwise):
    one entry per dim — None, axis name, or a list of axis names."""
    sh = getattr(a, "sharding", None)
    spec = getattr(sh, "spec", None)
    if spec is None:
        return None
    out = []
    for part in tuple(spec):
        if part is None:
            out.append(None)
        elif isinstance(part, (tuple, list)):
            out.append(list(part))
        else:
            out.append(str(part))
    return out


def _spec_sharding(mesh, saved_spec, shape):
    """Rebuild a NamedSharding on the CURRENT mesh from a saved spec.

    Resharding onto a different mesh is allowed: axes the current mesh
    does not have (or that no longer divide the dim) degrade to None —
    that dim comes back replicated rather than failing the restore."""
    from jax.sharding import NamedSharding, PartitionSpec

    parts = []
    for i, part in enumerate(saved_spec or []):
        names = [part] if isinstance(part, str) else list(part or [])
        names = [n for n in names if n in mesh.shape]
        if not names:
            parts.append(None)
            continue
        factor = 1
        for n in names:
            factor *= int(mesh.shape[n])
        if i >= len(shape) or shape[i] % factor:
            parts.append(None)
        else:
            parts.append(names[0] if len(names) == 1 else tuple(names))
    return NamedSharding(mesh, PartitionSpec(*parts))


def save_sharded(directory: str, arrays: Dict[str, jax.Array],
                 extra: Optional[dict] = None) -> str:
    """Write ``arrays`` (possibly sharded jax arrays) under ``directory``.

    Every distinct global slice is written exactly once globally: a shard
    is saved iff its ``replica_id == 0`` (for replicated arrays that is
    one device somewhere; for sharded arrays, one holder per slice).
    Safe to call from every process; each writes only its own files.
    """
    os.makedirs(directory, exist_ok=True)
    proc = jax.process_index()
    nproc = jax.process_count()
    # a re-save into a crashed attempt's directory (elastic restart)
    # must not let is_committed() satisfy on the DEAD attempt's markers
    # while this attempt is still writing: each process retracts its own
    # commit FIRST (process 0 also retracts the meta the committed check
    # reads), so only markers from the current attempt can commit
    try:
        os.unlink(os.path.join(directory, f"DONE.p{proc}"))
    except FileNotFoundError:
        pass
    if proc == 0:
        try:
            os.unlink(os.path.join(directory, "ckpt_meta.json"))
        except FileNotFoundError:
            pass
    with (_tel.span("checkpoint.save_sharded", {"process": proc})
          if _tel._ENABLED else _tel.NULL_SPAN):
        return _save_sharded_impl(directory, arrays, extra, proc, nproc)


def _save_sharded_impl(directory, arrays, extra, proc, nproc):
    pieces = {}  # npz key -> numpy data
    index = []  # [{name, key, bounds}]
    for name, a in arrays.items():
        a = jax.numpy.asarray(a)
        for shard in a.addressable_shards:
            if shard.replica_id != 0:
                continue
            key = str(len(pieces))
            pieces[key] = _np.asarray(shard.data)
            index.append({
                "name": name,
                "key": key,
                "bounds": _norm_bounds(shard.index, a.shape),
            })
    _np.savez(os.path.join(directory, f"shards_p{proc}.npz"), **pieces)
    with open(os.path.join(directory, f"index_p{proc}.json"), "w") as f:
        json.dump(index, f)
    if proc == 0:
        meta = {
            "format": "mxnet_tpu-sharded-v1",
            "process_count": nproc,
            "arrays": {
                name: {"shape": list(a.shape), "dtype": str(a.dtype),
                       "spec": _encode_spec(a)}
                for name, a in
                ((n, jax.numpy.asarray(v)) for n, v in arrays.items())
            },
        }
        if extra is not None:
            meta["extra"] = extra
        with open(os.path.join(directory, "ckpt_meta.json"), "w") as f:
            json.dump(meta, f)
    # commit marker LAST: a partially-written process never commits
    with open(os.path.join(directory, f"DONE.p{proc}"), "w") as f:
        f.write("ok")
    _tel.instant("checkpoint.shard_commit",
                 {"process": proc, "path": directory})
    return directory


def is_committed(directory: str) -> bool:
    meta_path = os.path.join(directory, "ckpt_meta.json")
    if not os.path.exists(meta_path):
        return False
    try:
        with open(meta_path) as f:
            nproc = json.load(f).get("process_count", 1)
    except (OSError, ValueError):
        # a torn/mid-write meta is simply "not committed yet" — pollers
        # (serving.CheckpointWatcher) retry on their next tick
        return False
    return all(
        os.path.exists(os.path.join(directory, f"DONE.p{k}"))
        for k in range(nproc)
    )


def commit_token(directory: str) -> Optional[str]:
    """Identity of a COMMITTED checkpoint's content, None otherwise.

    ``save_sharded`` retracts and rewrites ``ckpt_meta.json`` on every
    save, so its mtime changes whenever the directory's content does —
    a poller comparing tokens sees exactly the commits, never a
    half-written attempt (which has no meta / missing DONE markers)."""
    if not is_committed(directory):
        return None
    try:
        st = os.stat(os.path.join(directory, "ckpt_meta.json"))
    except OSError:
        return None
    return f"{os.path.normpath(directory)}@{st.st_mtime_ns}"


def latest_committed(directory: str):
    """Newest committed checkpoint under ``directory``: the directory
    itself, or any immediate subdirectory (the ``save_checkpoint(dir,
    step=N)`` -> ``dir/step_N`` layout). Returns ``(path, token)`` or
    None when nothing is committed yet."""
    candidates = [directory]
    try:
        for name in os.listdir(directory):
            sub = os.path.join(directory, name)
            if os.path.isdir(sub):
                candidates.append(sub)
    except OSError:
        return None
    best = None
    for cand in candidates:
        if not is_committed(cand):
            continue
        mtime = os.stat(os.path.join(cand, "ckpt_meta.json")).st_mtime_ns
        if best is None or mtime > best[0]:
            best = (mtime, cand)
    if best is None:
        return None
    return best[1], commit_token(best[1])


class _PieceReader:
    """Lazy per-file npz access: zip members are read on first use, so a
    restoring process touches only the pieces overlapping its shards."""

    def __init__(self, directory):
        self._dir = directory
        self._files = {}

    def get(self, fname, key):
        f = self._files.get(fname)
        if f is None:
            f = self._files[fname] = _np.load(
                os.path.join(self._dir, fname))
        return f[key]

    def close(self):
        for f in self._files.values():
            f.close()


def load_sharded(
    directory: str,
    shardings: Union[None, Dict[str, jax.sharding.Sharding],
                     Callable[[str], Optional[jax.sharding.Sharding]]] = None,
    mesh=None,
) -> Dict[str, jax.Array]:
    """Re-assemble the saved arrays onto the CURRENT devices.

    ``shardings`` maps array name -> target ``jax.sharding.Sharding``
    (dict or callable; None / missing name falls back). The target may
    differ from the layout at save time — each addressable shard's
    global slice is assembled from whichever saved pieces overlap it,
    so no process ever materializes the full tree.

    ``mesh`` — the NamedSharded round-trip path: arrays with no explicit
    target re-place under their SAVED PartitionSpec on this mesh (axes
    the mesh lacks, or that no longer divide, come back replicated).
    With neither ``shardings`` nor ``mesh``, placement is single-device.
    """
    with (_tel.span("checkpoint.load_sharded")
          if _tel._ENABLED else _tel.NULL_SPAN):
        return _load_sharded_impl(directory, shardings, mesh)


def _load_sharded_impl(directory, shardings=None, mesh=None):
    if not is_committed(directory):
        raise MXNetError(
            f"sharded checkpoint {directory} is not committed "
            "(missing DONE markers or ckpt_meta.json)")
    with open(os.path.join(directory, "ckpt_meta.json")) as f:
        meta = json.load(f)
    pieces: Dict[str, list] = {}
    for k in range(meta["process_count"]):
        with open(os.path.join(directory, f"index_p{k}.json")) as f:
            for ent in json.load(f):
                pieces.setdefault(ent["name"], []).append(
                    (ent["bounds"], f"shards_p{k}.npz", ent["key"]))
    reader = _PieceReader(directory)
    get_sharding = shardings if callable(shardings) else (
        (shardings or {}).get)
    out = {}
    try:
        for name, spec in meta["arrays"].items():
            shape = tuple(spec["shape"])
            dtype = _np.dtype(spec["dtype"])
            sharding = get_sharding(name)
            if sharding is None and mesh is not None:
                # NamedSharded round-trip: re-place under the spec the
                # array was SAVED with, on the restoring mesh
                sharding = _spec_sharding(mesh, spec.get("spec"), shape)
            if sharding is None:
                sharding = jax.sharding.SingleDeviceSharding(
                    jax.local_devices()[0])
            saved = pieces.get(name, [])

            def cb(index, _shape=shape, _dtype=dtype, _saved=saved,
                   _name=name):
                lo = [sl.indices(d)[0] for sl, d in zip(index, _shape)]
                hi = [sl.indices(d)[1] for sl, d in zip(index, _shape)]
                region = _np.empty(
                    [h - l for l, h in zip(lo, hi)], _dtype)
                covered = 0
                for bounds, fname, key in _saved:
                    olo = [max(l, b[0]) for l, b in zip(lo, bounds)]
                    ohi = [min(h, b[1]) for h, b in zip(hi, bounds)]
                    if any(a >= b for a, b in zip(olo, ohi)):
                        continue
                    data = reader.get(fname, key)
                    src = tuple(
                        slice(a - b[0], c - b[0])
                        for a, c, b in zip(olo, ohi, bounds))
                    dst = tuple(
                        slice(a - l, c - l)
                        for a, c, l in zip(olo, ohi, lo))
                    region[dst] = data[src]
                    vol = 1
                    for a, c in zip(olo, ohi):
                        vol *= c - a
                    covered += vol
                want = 1
                for l, h in zip(lo, hi):
                    want *= h - l
                if covered != want:
                    # replica-0 pieces are disjoint, so coverage volume
                    # equals region volume iff every element was filled
                    raise MXNetError(
                        f"checkpoint piece coverage hole for {_name}: "
                        f"{covered}/{want} elements")
                return region

            out[name] = jax.make_array_from_callback(shape, sharding, cb)
            # materialize before the reader is closed
            jax.block_until_ready(out[name])
    finally:
        reader.close()
    return out
