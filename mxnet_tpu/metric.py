"""Evaluation metrics (reference: ``python/mxnet/metric.py`` [unverified]).

``update()`` calls ``.asnumpy()`` on its inputs — this is THE host sync point
of a training loop, exactly as in the reference (SURVEY.md §3.3).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as _np

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = [
    "EvalMetric",
    "Accuracy",
    "TopKAccuracy",
    "F1",
    "MAE",
    "MSE",
    "RMSE",
    "CrossEntropy",
    "NegativeLogLikelihood",
    "PearsonCorrelation",
    "Perplexity",
    "Loss",
    "CompositeEvalMetric",
    "CustomMetric",
    "np",
    "create",
]

_REGISTRY: Dict[str, type] = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def alias(*names):
    def deco(klass):
        for n in names:
            _REGISTRY[n.lower()] = klass
        return klass

    return deco


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    key = str(metric).lower()
    if key not in _REGISTRY:
        raise MXNetError(f"metric {metric!r} is not registered")
    return _REGISTRY[key](*args, **kwargs)


def _as_numpy(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


def check_label_shapes(labels, preds, wrap=False, shape=False):
    if not shape:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError(
            f"Shape of labels {label_shape} does not match shape of predictions {pred_shape}"
        )
    if wrap:
        labels, preds = _as_list(labels), _as_list(preds)
    return labels, preds


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return f"EvalMetric: {dict(zip(*self.get()))}"

    def get_config(self):
        config = self._kwargs.copy()
        config.update(
            {
                "metric": self.__class__.__name__,
                "name": self.name,
                "output_names": self.output_names,
                "label_names": self.label_names,
            }
        )
        return config

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):  # pragma: no cover - abstract
        raise NotImplementedError

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        name, value = _as_list(name), _as_list(value)
        return list(zip(name, value))


@register
@alias("acc")
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names,
                         axis=axis)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(_as_list(labels), _as_list(preds), True)
        for label, pred in zip(labels, preds):
            pred, label = _as_numpy(pred), _as_numpy(label)
            if pred.ndim > label.ndim:
                pred = _np.argmax(pred, axis=self.axis)
            pred = pred.astype("int32").ravel()
            label = label.astype("int32").ravel()
            check_label_shapes(label, pred, shape=True)
            self.sum_metric += float((pred == label).sum())
            self.num_inst += len(pred)


@register
@alias("top_k_accuracy", "top_k_acc")
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names,
                         top_k=top_k)
        self.top_k = top_k
        if self.top_k <= 1:
            raise MXNetError("Use Accuracy for top_k == 1")
        self.name += f"_{self.top_k}"

    def update(self, labels, preds):
        labels, preds = check_label_shapes(_as_list(labels), _as_list(preds), True)
        for label, pred in zip(labels, preds):
            pred, label = _as_numpy(pred), _as_numpy(label).astype("int32")
            assert pred.ndim == 2, "Predictions should be 2 dims"
            num_samples = pred.shape[0]
            num_classes = pred.shape[1]
            top_k = min(num_classes, self.top_k)  # clamp BEFORE argpartition
            if top_k == num_classes:
                self.sum_metric += float(num_samples)  # every label is in top-k
                self.num_inst += num_samples
                continue
            pred = _np.argpartition(pred.astype("float32"), -top_k)
            for j in range(top_k):
                self.sum_metric += float(
                    (pred[:, num_classes - 1 - j].ravel() == label.ravel()).sum()
                )
            self.num_inst += num_samples


@register
class F1(EvalMetric):
    """Binary F1. average='macro' (reference default): mean of per-batch F1
    scores; 'micro': F1 of the cumulative tp/fp/fn counts."""

    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names=output_names, label_names=label_names)
        self.average = average
        self.reset_stats()

    def reset_stats(self):
        self.tp = self.fp = self.fn = 0.0

    def reset(self):
        super().reset()
        self.reset_stats()

    @staticmethod
    def _f1(tp, fp, fn):
        precision = tp / (tp + fp) if tp + fp > 0 else 0.0
        recall = tp / (tp + fn) if tp + fn > 0 else 0.0
        if precision + recall > 0:
            return 2 * precision * recall / (precision + recall)
        return 0.0

    def update(self, labels, preds):
        labels, preds = check_label_shapes(_as_list(labels), _as_list(preds), True)
        for label, pred in zip(labels, preds):
            pred, label = _as_numpy(pred), _as_numpy(label)
            if pred.ndim > 1:
                pred = _np.argmax(pred, axis=-1)
            pred = pred.ravel().astype("int32")
            label = label.ravel().astype("int32")
            tp = float(((pred == 1) & (label == 1)).sum())
            fp = float(((pred == 1) & (label == 0)).sum())
            fn = float(((pred == 0) & (label == 1)).sum())
            if self.average == "macro":
                self.sum_metric += self._f1(tp, fp, fn)
                self.num_inst += 1
            else:  # micro: cumulative counts
                self.tp += tp
                self.fp += fp
                self.fn += fn
                self.sum_metric = self._f1(self.tp, self.fp, self.fn)
                self.num_inst = 1


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(_as_list(labels), _as_list(preds), True)
        for label, pred in zip(labels, preds):
            label, pred = _as_numpy(label), _as_numpy(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.ndim == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += float(_np.abs(label - pred).mean())
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(_as_list(labels), _as_list(preds), True)
        for label, pred in zip(labels, preds):
            label, pred = _as_numpy(label), _as_numpy(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.ndim == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += float(((label - pred) ** 2).mean())
            self.num_inst += 1


@register
class RMSE(MSE):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.sqrt(self.sum_metric / self.num_inst))


@register
@alias("ce")
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names,
                         eps=eps)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(_as_list(labels), _as_list(preds), True)
        for label, pred in zip(labels, preds):
            label, pred = _as_numpy(label), _as_numpy(pred)
            label = label.ravel()
            assert label.shape[0] == pred.shape[0]
            prob = pred[_np.arange(label.shape[0]), label.astype("int64")]
            self.sum_metric += float((-_np.log(prob + self.eps)).sum())
            self.num_inst += label.shape[0]


@register
@alias("nll_loss")
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(eps=eps, name=name, output_names=output_names,
                         label_names=label_names)


@register
@alias("pearsonr")
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(_as_list(labels), _as_list(preds), True)
        for label, pred in zip(labels, preds):
            label, pred = _as_numpy(label), _as_numpy(pred)
            check_label_shapes(label, pred, False, True)
            self.sum_metric += float(
                _np.corrcoef(pred.ravel(), label.ravel())[0, 1]
            )
            self.num_inst += 1


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names,
                         ignore_label=ignore_label, axis=axis)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        assert len(labels) == len(preds)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label, pred = _as_numpy(label), _as_numpy(pred)
            label = label.reshape(-1).astype("int64")
            pred = pred.reshape(-1, pred.shape[-1])
            prob = pred[_np.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label).astype(prob.dtype)
                num -= int(ignore.sum())
                prob = prob * (1 - ignore) + ignore
            loss -= float(_np.log(_np.maximum(1e-10, prob)).sum())
            num += prob.shape[0]
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class Loss(EvalMetric):
    """Mean of whatever loss arrays are passed as preds."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names)

    def update(self, _, preds):
        for pred in _as_list(preds):
            loss = float(_as_numpy(pred).sum())
            self.sum_metric += loss
            self.num_inst += _as_numpy(pred).size


@register
class TotalLoss(Loss):
    pass


class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = f"custom({name})"
        super().__init__(name, output_names=output_names, label_names=label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            labels, preds = check_label_shapes(_as_list(labels), _as_list(preds), True)
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label, pred = _as_numpy(label), _as_numpy(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                num_inst, sum_metric = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy eval function into a metric (reference ``metric.np``)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names)
        if metrics is None:
            metrics = []
        self.metrics = [create(i) for i in metrics]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        try:
            return self.metrics[index]
        except IndexError:
            return ValueError(f"Metric index {index} is out of range 0 and {len(self.metrics)}")

    def update_dict(self, labels, preds):
        if self.label_names is not None:
            labels = {name: label for name, label in labels.items()
                      if name in self.label_names}
        if self.output_names is not None:
            preds = {name: pred for name, pred in preds.items()
                     if name in self.output_names}
        for metric in self.metrics:
            metric.update_dict(labels, preds)

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        try:
            for metric in self.metrics:
                metric.reset()
        except AttributeError:
            pass

    def get(self):
        names = []
        values = []
        for metric in self.metrics:
            name, value = metric.get()
            names.extend(_as_list(name))
            values.extend(_as_list(value))
        return (names, values)
