"""Base utilities: errors, env-var config, small helpers.

TPU-native analogue of the reference's `python/mxnet/base.py` and
`3rdparty/dmlc-core` (`dmlc::GetEnv`, logging->exceptions) [unverified paths,
see SURVEY.md provenance note]. There is no C ABI here: the "backend" is
JAX/XLA in-process, so errors are ordinary Python exceptions and configuration
is plain environment variables read at point of use, mirroring the reference's
``MXNET_*`` env-var convention.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Callable, Optional, Sequence

__all__ = [
    "MXNetError",
    "NotSupportedForSymbolAPI",
    "get_env",
    "env_bool",
    "env_int",
    "env_str",
    "numeric_types",
    "string_types",
    "logger",
]

logger = logging.getLogger("mxnet_tpu")

numeric_types = (float, int)
string_types = (str,)


class MXNetError(RuntimeError):
    """Framework error type (reference: ``MXGetLastError`` -> MXNetError)."""


class NotSupportedForSymbolAPI(MXNetError):
    """Raised where the legacy symbolic API has no TPU-native equivalent."""


_ENV_REGISTRY: dict = {}


def get_env(name: str, default: Any, typ: Callable = str) -> Any:
    """Read ``MXNET_*``-style env var with a typed default.

    Analogue of ``dmlc::GetEnv`` [unverified]. Values are re-read on every
    call so tests can monkeypatch ``os.environ``.
    """
    _ENV_REGISTRY.setdefault(name, (default, typ))
    raw = os.environ.get(name)
    if raw is None:
        return default
    if typ is bool:
        return raw.lower() not in ("0", "false", "off", "")
    return typ(raw)


def env_bool(name: str, default: bool = False) -> bool:
    return get_env(name, default, bool)


def env_int(name: str, default: int = 0) -> int:
    return get_env(name, default, int)


def env_str(name: str, default: str = "") -> str:
    return get_env(name, default, str)


def list_env_registry() -> dict:
    """All env vars the framework has consulted (for docs/introspection)."""
    return dict(_ENV_REGISTRY)


def check_call(ret):  # pragma: no cover - compat shim, no C ABI exists
    """Compat no-op: the reference checked C-ABI return codes here."""
    return ret


def _as_list(obj) -> list:
    if obj is None:
        return []
    if isinstance(obj, (list, tuple)):
        return list(obj)
    return [obj]


def classproperty(func):
    class _ClassProperty:
        def __get__(self, _obj, owner):
            return func(owner)

    return _ClassProperty()
