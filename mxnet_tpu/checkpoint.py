"""Checkpoint subsystem (SURVEY.md §5: ONE implementation behind the three
reference APIs — Gluon save/load_parameters, HybridBlock.export, Trainer
save/load_states — plus step-level training checkpoints for restart-based
recovery, the reference's failure-recovery story).

Sharded/distributed arrays are handled by orbax (tensorstore) when present;
single-host falls back to the portable ``.params`` format."""

from __future__ import annotations

import json
import os
import pickle
from typing import Any, Dict, Optional

import jax
import numpy as _np

from . import telemetry as _tel
from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["save_checkpoint", "load_checkpoint", "latest_step",
           "CheckpointManager"]


def save_checkpoint(directory, step, net=None, trainer=None, extra=None,
                    train_step=None):
    """Write a resumable training checkpoint.

    Layout: ``{directory}/step_{N}/`` with model params, optimizer states
    and metadata. ``train_step`` (a ``parallel.TrainStep``) is saved via
    the SHARDED layout (``checkpoint_sharded``): every process writes its
    addressable shards under ``trainstep/`` — no gather, TP-sharded
    arrays are never materialized whole. net/trainer state is written by
    process 0 only (replicated by construction on those paths). Safe to
    call from every process.
    """
    with (_tel.span("checkpoint.save", {"step": int(step)})
          if _tel._ENABLED else _tel.NULL_SPAN):
        return _save_checkpoint(directory, step, net, trainer, extra,
                                train_step)


def _save_checkpoint(directory, step, net=None, trainer=None, extra=None,
                     train_step=None):
    path = os.path.join(directory, f"step_{step}")
    os.makedirs(path, exist_ok=True)
    if train_step is not None:
        # all processes participate; each writes only its own files
        train_step.save_checkpoint(os.path.join(path, "trainstep"))
    if jax.process_index() != 0:
        return path
    if train_step is not None:
        # wait for every process's shard commit marker before declaring
        # the STEP committed — process 0 must not outrun peers still
        # writing (a preemption in that window would otherwise leave a
        # COMMITTED-but-unloadable step that wedges every restart)
        from . import checkpoint_sharded as _cs
        import time as _time

        deadline = _time.monotonic() + 600
        sub = os.path.join(path, "trainstep")
        while not _cs.is_committed(sub):
            if _time.monotonic() > deadline:
                raise MXNetError(
                    f"timed out waiting for peer shard commits in {sub}")
            _time.sleep(0.2)
    if net is not None:
        net.save_parameters(os.path.join(path, "model.params"))
    if trainer is not None:
        trainer.save_states(os.path.join(path, "trainer.states"))
    meta = {"step": int(step), "format": "mxnet_tpu-ckpt-v1",
            "has_trainstep": train_step is not None}
    if extra:
        with open(os.path.join(path, "extra.pkl"), "wb") as f:
            pickle.dump(extra, f)
        meta["has_extra"] = True
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(meta, f)
    # commit marker last: partial checkpoints are never loaded
    with open(os.path.join(path, "COMMITTED"), "w") as f:
        f.write("ok")
    _tel.instant("checkpoint.commit", {"step": int(step), "path": path})
    return path


def _step_committed(path) -> bool:
    """A step is loadable iff its own marker exists AND, when it carries
    a sharded TrainStep payload, every process's shard commit landed."""
    if not os.path.exists(os.path.join(path, "COMMITTED")):
        return False
    meta_path = os.path.join(path, "meta.json")
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except (OSError, ValueError):
        return False
    if meta.get("has_trainstep"):
        from . import checkpoint_sharded as _cs

        return _cs.is_committed(os.path.join(path, "trainstep"))
    return True


def latest_step(directory) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        if name.startswith("step_") and _step_committed(
            os.path.join(directory, name)
        ):
            try:
                steps.append(int(name[5:]))
            except ValueError:
                continue
    return max(steps) if steps else None


def load_checkpoint(directory, step=None, net=None, trainer=None,
                    train_step=None):
    """Load the given (or latest committed) checkpoint; returns metadata."""
    with (_tel.span("checkpoint.restore",
                    {"step": -1 if step is None else int(step)})
          if _tel._ENABLED else _tel.NULL_SPAN):
        return _load_checkpoint(directory, step, net, trainer, train_step)


def _load_checkpoint(directory, step=None, net=None, trainer=None,
                     train_step=None):
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise MXNetError(f"no committed checkpoint under {directory}")
    path = os.path.join(directory, f"step_{step}")
    if not _step_committed(path):
        raise MXNetError(f"checkpoint {path} is not committed")
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    if train_step is not None:
        if not meta.get("has_trainstep"):
            raise MXNetError(
                f"checkpoint {path} was saved without a TrainStep payload; "
                "cannot restore train_step from it")
        train_step.load_checkpoint(os.path.join(path, "trainstep"))
    if net is not None:
        net.load_parameters(os.path.join(path, "model.params"))
    if trainer is not None:
        trainer.load_states(os.path.join(path, "trainer.states"))
    if meta.get("has_extra"):
        with open(os.path.join(path, "extra.pkl"), "rb") as f:
            meta["extra"] = pickle.load(f)
    return meta


class CheckpointManager:
    """Rolling checkpoint manager (keep last K; reference analogue:
    ``Module.save_checkpoint`` epoch files + manual cleanup)."""

    def __init__(self, directory, keep=3, interval=1):
        self.directory = directory
        self.keep = keep
        self.interval = interval

    def should_save(self, step) -> bool:
        return step % self.interval == 0

    def save(self, step, net=None, trainer=None, extra=None,
             train_step=None):
        path = save_checkpoint(self.directory, step, net, trainer, extra,
                               train_step=train_step)
        self._cleanup()
        return path

    def restore_latest(self, net=None, trainer=None, train_step=None):
        step = latest_step(self.directory)
        if step is None:
            return None
        return load_checkpoint(self.directory, step, net, trainer,
                               train_step=train_step)

    def _cleanup(self):
        if jax.process_index() != 0:
            return
        steps = sorted(
            int(n[5:]) for n in os.listdir(self.directory)
            if n.startswith("step_")
            and _step_committed(os.path.join(self.directory, n))
        )
        for s in steps[: -self.keep]:
            import shutil

            shutil.rmtree(os.path.join(self.directory, f"step_{s}"),
                          ignore_errors=True)
