"""Autograd: imperative tape + reverse-mode backward.

TPU-native analogue of the reference's autograd
(``src/imperative/imperative.cc`` ``RecordOp``/``Backward``, the nnvm
``Gradient`` pass, and ``python/mxnet/autograd.py`` [unverified]).

Design: while ``record()`` is active, every imperative op invocation whose
inputs connect to a gradient-requiring leaf is executed through ``jax.vjp``,
which returns the primal outputs plus a VJP closure holding residuals on
device. Tape nodes link VJP closures through their input NDArrays (the
``AGInfo`` analogue). ``backward()`` topologically sorts reachable nodes and
pulls cotangents backwards, accumulating into leaf ``.grad`` buffers honoring
``grad_req`` in {'write', 'add', 'null'}.

Because residuals are captured at call time, later in-place mutation of an
input cannot corrupt gradients — the role the reference's engine version
counters played is filled by functional capture.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as _np

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = [
    "record",
    "pause",
    "train_mode",
    "predict_mode",
    "is_recording",
    "is_training",
    "set_recording",
    "set_training",
    "backward",
    "grad",
    "mark_variables",
    "Function",
]

_STATE = threading.local()


def _st():
    if not hasattr(_STATE, "recording"):
        _STATE.recording = False
        _STATE.training = False
    return _STATE


def is_recording() -> bool:
    return _st().recording


def is_training() -> bool:
    return _st().training


def set_recording(flag: bool) -> bool:
    st = _st()
    prev, st.recording = st.recording, bool(flag)
    return prev


def set_training(flag: bool) -> bool:
    st = _st()
    prev, st.training = st.training, bool(flag)
    return prev


@contextlib.contextmanager
def _scope(recording: Optional[bool], training: Optional[bool]):
    st = _st()
    prev_r, prev_t = st.recording, st.training
    if recording is not None:
        st.recording = recording
    if training is not None:
        st.training = training
    try:
        yield
    finally:
        st.recording, st.training = prev_r, prev_t


def record(train_mode: bool = True):
    """Scope in which imperative ops are recorded for backward()."""
    return _scope(True, train_mode)


def pause(train_mode: bool = False):
    return _scope(False, train_mode)


def train_mode():
    return _scope(None, True)


def predict_mode():
    return _scope(None, False)


# --------------------------------------------------------------------- tape
class _Node:
    """One recorded invocation (reference: autograd tape node / AGInfo)."""

    __slots__ = ("vjp_fn", "inputs", "out_avals", "multi_out", "freed",
                 "bulk_key", "bwd_fn", "xs")

    def __init__(self, vjp_fn, inputs, out_avals, multi_out,
                 bulk_key=None, bwd_fn=None, xs=None):
        self.vjp_fn = vjp_fn
        self.inputs = inputs  # list of (NDArray | None) — None for untracked
        self.out_avals = out_avals  # [(shape, dtype)] per output
        self.multi_out = multi_out
        self.freed = False
        # bulked-backward support: a structural identity for the op's
        # computation (the per-op jit cache key), the pure (xs, ct) ->
        # input-cotangents callable, and the captured primal operands.
        # None bulk_key = node not bulkable (custom Function, staged
        # CachedOp, un-jittable op) — backward falls back to per-op replay.
        self.bulk_key = bulk_key
        self.bwd_fn = bwd_fn
        self.xs = xs


class _AGInfo:
    __slots__ = ("node", "index")

    def __init__(self, node: Optional[_Node], index: int = 0):
        self.node = node
        self.index = index


def _attach_grad(arr: NDArray, grad_req: str = "write"):
    """Mark ``arr`` as a gradient-requiring leaf (reference: attach_grad)."""
    if grad_req not in ("write", "add", "null"):
        raise MXNetError(f"invalid grad_req {grad_req!r}")
    arr._grad_req = grad_req
    if grad_req != "null":
        arr._grad = NDArray(jnp.zeros_like(arr.data))
    else:
        arr._grad = None
    arr._ag = _AGInfo(None)  # leaf marker


def _is_tracked(arr) -> bool:
    return isinstance(arr, NDArray) and arr._ag is not None


def _should_record(args) -> bool:
    return is_recording() and any(_is_tracked(a) for a in args)


def _record(fn: Callable, args, datas):
    """Execute ``fn`` under jax.vjp and build a tape node."""
    outs, vjp_fn = jax.vjp(fn, *datas)
    multi = isinstance(outs, (tuple, list))
    outs_t = tuple(outs) if multi else (outs,)
    avals = [(o.shape, o.dtype) for o in outs_t]
    inputs = [a if _is_tracked(a) else None for a in args]
    node = _Node(vjp_fn, inputs, avals, multi)
    return outs, node


def _has_float0(ct):
    cts = ct if isinstance(ct, (tuple, list)) else (ct,)
    return any(getattr(c, "dtype", None) == jax.dtypes.float0 for c in cts)


def _record_cached(fwd, bwd, fn, args, datas, bulk_key=None):
    """Tape node over CACHED jitted callables (imperative._fwd_jit /
    _bwd_jit): the forward is one pjit fast-path call, and the backward
    recomputes the forward inside one cached pjit instead of holding a
    per-call ``jax.vjp`` residual closure — eliminating the per-op
    linearization that profiled as the eager hot-loop bottleneck. The
    recompute trade is right for the dispatch-bound imperative path; a
    compute-bound training loop belongs in hybridize()/TrainStep."""
    outs = fwd(*datas)
    multi = isinstance(outs, (tuple, list))
    outs_t = tuple(outs) if multi else (outs,)
    avals = [(o.shape, o.dtype) for o in outs_t]
    inputs = [a if _is_tracked(a) else None for a in args]
    xs = tuple(datas)

    def vjp(ct):
        if _has_float0(ct):
            # float0 cotangents (int outputs) are host values jit cannot
            # take as operands — use the direct path for this rare case
            return jax.vjp(fn, *xs)[1](ct)
        return bwd(xs, ct)

    node = _Node(vjp, inputs, avals, multi,
                 bulk_key=bulk_key, bwd_fn=bwd, xs=xs)
    return outs, node


def _record_deferred(bwd, fn, args, out_avals, multi, bulk_key):
    """Tape node for a BULK-QUEUED op: primal operands are not concrete
    yet; the queue's flush writes them into ``node.xs`` before any
    backward can run (backward reads head values, which flushes)."""
    inputs = [a if _is_tracked(a) else None for a in args]
    node = _Node(None, inputs, out_avals, multi,
                 bulk_key=bulk_key, bwd_fn=bwd, xs=None)

    def vjp(ct):
        xs = node.xs
        if xs is None:
            from .imperative import flush_bulk

            flush_bulk()
            xs = node.xs
        if _has_float0(ct):
            return jax.vjp(fn, *xs)[1](ct)
        return bwd(xs, ct)

    node.vjp_fn = vjp
    return node


def _mark_output(nd: NDArray, node: _Node, index: int):
    nd._ag = _AGInfo(node, index)


# ------------------------------------------------------- bulked backward
# The reference answered per-op engine-push cost with bulked segments
# (``MXNET_GLUON_EXEC_BULK_SIZE``, ``src/imperative/cached_op.cc``
# [unverified]); our per-op cost is the per-EXECUTABLE round trip, so the
# analogue is: compile the whole tape traversal into ONE jitted program
# (each node's cached bwd inlines into it) keyed by the tape's structure.
# A stable training loop hits the same fingerprint every step: backward
# collapses from ~#ops launches to one. MXTPU_BULK_BWD=0 disables.
_BULK_BWD_CACHE: dict = {}
_BULK_BWD_CAP = 256


def _bulk_enabled() -> bool:
    from .base import env_bool

    return env_bool("MXTPU_BULK_BWD", True)


def _try_bulk_backward(head_targets, order, retain_graph):
    """One-launch backward. head_targets: [(node, out_idx, ct_or_None,
    head_aval)] — ct None means the default ones cotangent (built inside
    the trace, saving its launch too). Returns {id(leaf): total} or None
    when the tape is not bulkable."""
    if not _bulk_enabled() or is_recording() or len(order) < 2:
        return None
    pos_of = {id(n): i for i, n in enumerate(order)}
    leaf_slots: dict = {}
    leaf_arrs: List[NDArray] = []
    desc = []
    bwds = []
    xs_all = []
    for n in order:
        if n.bulk_key is None or n.bwd_fn is None or n.xs is None \
                or n.freed:
            return None
        for (_, dtype) in n.out_avals:
            if not (jnp.issubdtype(dtype, jnp.floating)
                    or jnp.issubdtype(dtype, jnp.complexfloating)):
                return None  # float0 cotangents: per-op fallback
        wiring = []
        for arr in n.inputs:
            if arr is None or arr._ag is None:
                wiring.append(None)
            elif arr._ag.node is None:
                lid = id(arr)
                if lid not in leaf_slots:
                    leaf_slots[lid] = len(leaf_arrs)
                    leaf_arrs.append(arr)
                wiring.append(("leaf", leaf_slots[lid]))
            else:
                p = pos_of.get(id(arr._ag.node))
                if p is None:
                    return None
                wiring.append(("node", p, arr._ag.index))
        xs_avals = tuple(
            (x.shape, str(x.dtype)) if hasattr(x, "shape")
            else ("py", type(x).__name__) for x in n.xs)
        desc.append((n.bulk_key, tuple(
            (s, str(jnp.dtype(d))) for s, d in n.out_avals),
            tuple(wiring), xs_avals, n.multi_out))
        bwds.append(n.bwd_fn)
        xs_all.append(n.xs)

    heads_desc = []
    head_ops = []
    for node, oi, ct, aval in head_targets:
        p = pos_of.get(id(node))
        if p is None:
            return None
        heads_desc.append((p, oi, ct is not None, aval))
        if ct is not None:
            head_ops.append(ct)

    fp = (tuple(desc), tuple(heads_desc),
          tuple((a.data.shape, str(a.data.dtype)) for a in leaf_arrs))
    entry = _BULK_BWD_CACHE.get(fp)
    if entry is None:
        # static reachability: which nodes fire and which leaves receive
        # cotangents is a pure function of the structure — decide once
        have = set()
        for p, oi, _, _ in heads_desc:
            have.add((p, oi))
        fires = []
        leaf_hit = set()
        for pos, (_, out_avals, wiring, _, _) in enumerate(desc):
            fire = any((pos, i) in have for i in range(len(out_avals)))
            fires.append(fire)
            if not fire:
                continue
            for w in wiring:
                if w is None:
                    continue
                if w[0] == "leaf":
                    leaf_hit.add(w[1])
                else:
                    have.add((w[1], w[2]))
        hit_list = sorted(leaf_hit)

        def traversal(xs_all, head_ops):
            cot: dict = {}
            gi = 0
            for (p, oi, has, (hshape, hdtype)) in heads_desc:
                if has:
                    ct = head_ops[gi]
                    gi += 1
                else:
                    ct = jnp.ones(hshape, hdtype)
                prev = cot.get((p, oi))
                cot[(p, oi)] = ct if prev is None else prev + ct
            totals = {}
            for pos, (_, out_avals, wiring, _, multi) in enumerate(desc):
                if not fires[pos]:
                    continue
                outs = []
                for i, (shape, dtype) in enumerate(out_avals):
                    c = cot.pop((pos, i), None)
                    outs.append(jnp.zeros(shape, dtype) if c is None else c)
                ct_arg = tuple(outs) if multi else outs[0]
                in_cts = bwds[pos](xs_all[pos], ct_arg)
                for w, ict in zip(wiring, in_cts):
                    if w is None or ict is None:
                        continue
                    if w[0] == "leaf":
                        prev = totals.get(w[1])
                        totals[w[1]] = ict if prev is None else prev + ict
                    else:
                        key = (w[1], w[2])
                        prev = cot.get(key)
                        cot[key] = ict if prev is None else prev + ict
            return tuple(totals[s] for s in hit_list)

        if len(_BULK_BWD_CACHE) >= _BULK_BWD_CAP:
            _BULK_BWD_CACHE.pop(next(iter(_BULK_BWD_CACHE)))
        entry = _BULK_BWD_CACHE[fp] = (jax.jit(traversal), hit_list)

    fn, hit_list = entry
    try:
        results = fn(tuple(xs_all), tuple(head_ops))
    except Exception:  # structural edge the trace rejects: fall back
        _BULK_BWD_CACHE.pop(fp, None)
        return None
    if not retain_graph:
        for n in order:
            n.vjp_fn = None
            n.bwd_fn = None
            n.xs = None
            n.freed = True
    return [(leaf_arrs[s], r) for s, r in zip(hit_list, results)]


# ----------------------------------------------------------------- backward
_BACKWARD_EPOCH = [0]  # bumped per traversal; custom self-recording
# gradient writers (sparse embedding) use it for 'write' reset semantics


def backward(
    heads: Sequence[NDArray],
    head_grads: Optional[Sequence[Optional[NDArray]]] = None,
    retain_graph: bool = False,
    train_mode: bool = True,
):
    """Reverse pass from ``heads`` (reference: ``Imperative::Backward``)."""
    from .imperative import flush_bulk

    flush_bulk()  # resolve any queued forward ops (fills node.xs)
    _BACKWARD_EPOCH[0] += 1
    heads = list(heads)
    if head_grads is None:
        head_grads = [None] * len(heads)
    if len(head_grads) != len(heads):
        raise MXNetError("head_grads length mismatch")

    # output cotangent accumulator keyed by (id(node), out_index)
    cotangents = {}
    # leaf cotangent accumulator keyed by id(leaf NDArray)
    leaf_cts = {}
    leaves = {}

    roots = []
    head_targets = []  # (node, out_idx, ct_or_None, head_aval) for bulk
    bulk_ok = True
    for h, hg in zip(heads, head_grads):
        if h._ag is None:
            raise MXNetError(
                "cannot differentiate: output is not connected to any "
                "variable created under autograd.record() with attach_grad()"
            )
        node = h._ag.node
        if node is None:  # head IS a leaf variable
            g = hg.data if isinstance(hg, NDArray) else (
                hg if hg is not None else jnp.ones_like(h.data))
            leaf_cts.setdefault(id(h), []).append(g)
            leaves[id(h)] = h
            bulk_ok = False
            continue
        ct = hg.data if isinstance(hg, NDArray) else hg
        head_targets.append(
            (node, h._ag.index, ct, (h.data.shape, h.data.dtype)))
        roots.append(node)

    order = _toposort(roots)

    if bulk_ok:
        bulk = _try_bulk_backward(head_targets, order, retain_graph)
        if bulk is not None:
            for leaf, total in bulk:
                req = leaf._grad_req
                if req == "null":
                    continue
                if leaf._grad is None:
                    leaf._grad = NDArray(jnp.zeros_like(leaf.data))
                total = total.astype(leaf.data.dtype)
                if req == "write":
                    leaf._grad._rebind(total)
                elif req == "add":
                    leaf._grad._rebind(leaf._grad.data + total)
            return

    for node, oi, ct, (hshape, hdtype) in head_targets:
        g = ct if ct is not None else jnp.ones(hshape, hdtype)
        cotangents.setdefault((id(node), oi), []).append(g)

    node_by_id = {id(n): n for n in order}
    for node in order:  # already reverse topological
        outs = []
        any_ct = False
        for i, (shape, dtype) in enumerate(node.out_avals):
            cts = cotangents.pop((id(node), i), None)
            if cts:
                any_ct = True
                ct = cts[0]
                for extra in cts[1:]:
                    ct = ct + extra
            else:
                # jax.vjp requires float0 cotangents for non-float outputs
                # (e.g. argmax/aux int outputs of a staged CachedOp call)
                if jnp.issubdtype(dtype, jnp.floating) or jnp.issubdtype(
                    dtype, jnp.complexfloating
                ):
                    ct = jnp.zeros(shape, dtype)
                else:
                    ct = _np.zeros(shape, jax.dtypes.float0)
            outs.append(ct)
        if not any_ct:
            continue
        if node.freed:
            raise MXNetError(
                "graph already freed: call backward(retain_graph=True) to "
                "backprop through the same graph twice"
            )
        ct_arg = tuple(outs) if node.multi_out else outs[0]
        in_cts = node.vjp_fn(ct_arg)
        if not retain_graph:
            node.vjp_fn = None
            node.bwd_fn = None
            node.xs = None  # or the primal operand buffers stay alive
            node.freed = True
        for arr, ict in zip(node.inputs, in_cts):
            if arr is None or ict is None:
                continue
            if hasattr(ict, "dtype") and ict.dtype == jax.dtypes.float0:
                continue
            sub = arr._ag.node
            if sub is None:
                leaf_cts.setdefault(id(arr), []).append(ict)
                leaves[id(arr)] = arr
            else:
                cotangents.setdefault((id(sub), arr._ag.index), []).append(ict)

    # write leaf grads honoring grad_req
    for lid, cts in leaf_cts.items():
        leaf = leaves[lid]
        total = cts[0]
        for extra in cts[1:]:
            total = total + extra
        req = leaf._grad_req
        if req == "null":
            continue
        if leaf._grad is None:
            leaf._grad = NDArray(jnp.zeros_like(leaf.data))
        if req == "write":
            leaf._grad._rebind(total.astype(leaf.data.dtype))
        elif req == "add":
            leaf._grad._rebind(leaf._grad.data + total.astype(leaf.data.dtype))


def _toposort(roots: List[_Node]) -> List[_Node]:
    """Reverse-topological order (outputs first) over the tape DAG."""
    visited = set()
    post = []
    # iterative DFS to survive deep chains (RNN tapes)
    for root in roots:
        if id(root) in visited:
            continue
        stack = [(root, iter(_parents(root)))]
        visited.add(id(root))
        while stack:
            node, it = stack[-1]
            advanced = False
            for p in it:
                if id(p) not in visited:
                    visited.add(id(p))
                    stack.append((p, iter(_parents(p))))
                    advanced = True
                    break
            if not advanced:
                post.append(node)
                stack.pop()
    post.reverse()  # outputs first
    return post


def _parents(node: _Node):
    for arr in node.inputs:
        if arr is not None and arr._ag is not None and arr._ag.node is not None:
            yield arr._ag.node


# ------------------------------------------------------------------ helpers
def mark_variables(variables, gradients, grad_reqs="write"):
    """Reference: ``autograd.mark_variables``."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad_req = req
        v._grad = g
        v._ag = _AGInfo(None)


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode=True):
    """Compute and RETURN grads of heads wrt variables (reference API)."""
    if create_graph:
        raise MXNetError("create_graph=True (higher order) not supported yet")
    variables = list(variables)
    saved = [(v._grad, v._grad_req, v._ag) for v in variables]
    for v in variables:
        if v._ag is None:
            raise MXNetError("variables must be tracked (attach_grad or used "
                             "as recorded outputs)")
        v._grad = NDArray(jnp.zeros_like(v.data))
        if v._grad_req == "null":
            v._grad_req = "write"
    heads = [heads] if isinstance(heads, NDArray) else list(heads)
    backward(heads, head_grads, retain_graph=bool(retain_graph))
    outs = [v._grad for v in variables]
    for v, (g, req, ag) in zip(variables, saved):
        v._grad, v._grad_req = g, req
    return outs


def get_symbol(x):  # legacy API stub
    raise MXNetError("the symbolic tape export has no TPU-native equivalent; "
                     "use HybridBlock.export instead")


class Function:
    """Custom differentiable function (reference: ``autograd.Function``).

    Subclass and implement ``forward(self, *inputs)`` and
    ``backward(self, *output_grads)`` operating on NDArrays.
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *arrays):
        self._saved = arrays

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):  # pragma: no cover - abstract
        raise NotImplementedError

    def backward(self, *output_grads):  # pragma: no cover - abstract
        raise NotImplementedError

    def __call__(self, *inputs):
        with pause():
            outputs = self.forward(*inputs)
        if not is_recording() or not any(_is_tracked(i) for i in inputs):
            return outputs
        multi = isinstance(outputs, (tuple, list))
        outs_t = tuple(outputs) if multi else (outputs,)

        func = self

        def vjp_fn(cts):
            cts_t = cts if isinstance(cts, (tuple, list)) else (cts,)
            with pause():
                in_grads = func.backward(*[NDArray(c) for c in cts_t])
            in_grads_t = in_grads if isinstance(in_grads, (tuple, list)) else (in_grads,)
            return tuple(
                g.data if isinstance(g, NDArray) else g for g in in_grads_t
            )

        avals = [(o.data.shape, o.data.dtype) for o in outs_t]
        node = _Node(vjp_fn, [a if _is_tracked(a) else None for a in inputs],
                     avals, multi)
        for i, o in enumerate(outs_t):
            _mark_output(o, node, i)
        return outputs
