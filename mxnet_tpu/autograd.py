"""Autograd: imperative tape + reverse-mode backward.

TPU-native analogue of the reference's autograd
(``src/imperative/imperative.cc`` ``RecordOp``/``Backward``, the nnvm
``Gradient`` pass, and ``python/mxnet/autograd.py`` [unverified]).

Design: while ``record()`` is active, every imperative op invocation whose
inputs connect to a gradient-requiring leaf is executed through ``jax.vjp``,
which returns the primal outputs plus a VJP closure holding residuals on
device. Tape nodes link VJP closures through their input NDArrays (the
``AGInfo`` analogue). ``backward()`` topologically sorts reachable nodes and
pulls cotangents backwards, accumulating into leaf ``.grad`` buffers honoring
``grad_req`` in {'write', 'add', 'null'}.

Because residuals are captured at call time, later in-place mutation of an
input cannot corrupt gradients — the role the reference's engine version
counters played is filled by functional capture.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as _np

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = [
    "record",
    "pause",
    "train_mode",
    "predict_mode",
    "is_recording",
    "is_training",
    "set_recording",
    "set_training",
    "backward",
    "grad",
    "mark_variables",
    "Function",
]

_STATE = threading.local()


def _st():
    if not hasattr(_STATE, "recording"):
        _STATE.recording = False
        _STATE.training = False
    return _STATE


def is_recording() -> bool:
    return _st().recording


def is_training() -> bool:
    return _st().training


def set_recording(flag: bool) -> bool:
    st = _st()
    prev, st.recording = st.recording, bool(flag)
    return prev


def set_training(flag: bool) -> bool:
    st = _st()
    prev, st.training = st.training, bool(flag)
    return prev


@contextlib.contextmanager
def _scope(recording: Optional[bool], training: Optional[bool]):
    st = _st()
    prev_r, prev_t = st.recording, st.training
    if recording is not None:
        st.recording = recording
    if training is not None:
        st.training = training
    try:
        yield
    finally:
        st.recording, st.training = prev_r, prev_t


def record(train_mode: bool = True):
    """Scope in which imperative ops are recorded for backward()."""
    return _scope(True, train_mode)


def pause(train_mode: bool = False):
    return _scope(False, train_mode)


def train_mode():
    return _scope(None, True)


def predict_mode():
    return _scope(None, False)


# --------------------------------------------------------------------- tape
class _Node:
    """One recorded invocation (reference: autograd tape node / AGInfo)."""

    __slots__ = ("vjp_fn", "inputs", "out_avals", "multi_out", "freed")

    def __init__(self, vjp_fn, inputs, out_avals, multi_out):
        self.vjp_fn = vjp_fn
        self.inputs = inputs  # list of (NDArray | None) — None for untracked
        self.out_avals = out_avals  # [(shape, dtype)] per output
        self.multi_out = multi_out
        self.freed = False


class _AGInfo:
    __slots__ = ("node", "index")

    def __init__(self, node: Optional[_Node], index: int = 0):
        self.node = node
        self.index = index


def _attach_grad(arr: NDArray, grad_req: str = "write"):
    """Mark ``arr`` as a gradient-requiring leaf (reference: attach_grad)."""
    if grad_req not in ("write", "add", "null"):
        raise MXNetError(f"invalid grad_req {grad_req!r}")
    arr._grad_req = grad_req
    if grad_req != "null":
        arr._grad = NDArray(jnp.zeros_like(arr.data))
    else:
        arr._grad = None
    arr._ag = _AGInfo(None)  # leaf marker


def _is_tracked(arr) -> bool:
    return isinstance(arr, NDArray) and arr._ag is not None


def _should_record(args) -> bool:
    return is_recording() and any(_is_tracked(a) for a in args)


def _record(fn: Callable, args, datas):
    """Execute ``fn`` under jax.vjp and build a tape node."""
    outs, vjp_fn = jax.vjp(fn, *datas)
    multi = isinstance(outs, (tuple, list))
    outs_t = tuple(outs) if multi else (outs,)
    avals = [(o.shape, o.dtype) for o in outs_t]
    inputs = [a if _is_tracked(a) else None for a in args]
    node = _Node(vjp_fn, inputs, avals, multi)
    return outs, node


def _has_float0(ct):
    cts = ct if isinstance(ct, (tuple, list)) else (ct,)
    return any(getattr(c, "dtype", None) == jax.dtypes.float0 for c in cts)


def _record_cached(fwd, bwd, fn, args, datas):
    """Tape node over CACHED jitted callables (imperative._fwd_jit /
    _bwd_jit): the forward is one pjit fast-path call, and the backward
    recomputes the forward inside one cached pjit instead of holding a
    per-call ``jax.vjp`` residual closure — eliminating the per-op
    linearization that profiled as the eager hot-loop bottleneck. The
    recompute trade is right for the dispatch-bound imperative path; a
    compute-bound training loop belongs in hybridize()/TrainStep."""
    outs = fwd(*datas)
    multi = isinstance(outs, (tuple, list))
    outs_t = tuple(outs) if multi else (outs,)
    avals = [(o.shape, o.dtype) for o in outs_t]
    inputs = [a if _is_tracked(a) else None for a in args]
    xs = tuple(datas)

    def vjp(ct):
        if _has_float0(ct):
            # float0 cotangents (int outputs) are host values jit cannot
            # take as operands — use the direct path for this rare case
            return jax.vjp(fn, *xs)[1](ct)
        return bwd(xs, ct)

    node = _Node(vjp, inputs, avals, multi)
    return outs, node


def _mark_output(nd: NDArray, node: _Node, index: int):
    nd._ag = _AGInfo(node, index)


# ----------------------------------------------------------------- backward
_BACKWARD_EPOCH = [0]  # bumped per traversal; custom self-recording
# gradient writers (sparse embedding) use it for 'write' reset semantics


def backward(
    heads: Sequence[NDArray],
    head_grads: Optional[Sequence[Optional[NDArray]]] = None,
    retain_graph: bool = False,
    train_mode: bool = True,
):
    """Reverse pass from ``heads`` (reference: ``Imperative::Backward``)."""
    _BACKWARD_EPOCH[0] += 1
    heads = list(heads)
    if head_grads is None:
        head_grads = [None] * len(heads)
    if len(head_grads) != len(heads):
        raise MXNetError("head_grads length mismatch")

    # output cotangent accumulator keyed by (id(node), out_index)
    cotangents = {}
    # leaf cotangent accumulator keyed by id(leaf NDArray)
    leaf_cts = {}
    leaves = {}

    roots = []
    for h, hg in zip(heads, head_grads):
        if h._ag is None:
            raise MXNetError(
                "cannot differentiate: output is not connected to any "
                "variable created under autograd.record() with attach_grad()"
            )
        g = hg.data if isinstance(hg, NDArray) else (hg if hg is not None else jnp.ones_like(h.data))
        node = h._ag.node
        if node is None:  # head IS a leaf variable
            leaf_cts.setdefault(id(h), []).append(g)
            leaves[id(h)] = h
            continue
        key = (id(node), h._ag.index)
        cotangents.setdefault(key, []).append(g)
        roots.append(node)

    order = _toposort(roots)

    node_by_id = {id(n): n for n in order}
    for node in order:  # already reverse topological
        outs = []
        any_ct = False
        for i, (shape, dtype) in enumerate(node.out_avals):
            cts = cotangents.pop((id(node), i), None)
            if cts:
                any_ct = True
                ct = cts[0]
                for extra in cts[1:]:
                    ct = ct + extra
            else:
                # jax.vjp requires float0 cotangents for non-float outputs
                # (e.g. argmax/aux int outputs of a staged CachedOp call)
                if jnp.issubdtype(dtype, jnp.floating) or jnp.issubdtype(
                    dtype, jnp.complexfloating
                ):
                    ct = jnp.zeros(shape, dtype)
                else:
                    ct = _np.zeros(shape, jax.dtypes.float0)
            outs.append(ct)
        if not any_ct:
            continue
        if node.freed:
            raise MXNetError(
                "graph already freed: call backward(retain_graph=True) to "
                "backprop through the same graph twice"
            )
        ct_arg = tuple(outs) if node.multi_out else outs[0]
        in_cts = node.vjp_fn(ct_arg)
        if not retain_graph:
            node.vjp_fn = None
            node.freed = True
        for arr, ict in zip(node.inputs, in_cts):
            if arr is None or ict is None:
                continue
            if hasattr(ict, "dtype") and ict.dtype == jax.dtypes.float0:
                continue
            sub = arr._ag.node
            if sub is None:
                leaf_cts.setdefault(id(arr), []).append(ict)
                leaves[id(arr)] = arr
            else:
                cotangents.setdefault((id(sub), arr._ag.index), []).append(ict)

    # write leaf grads honoring grad_req
    for lid, cts in leaf_cts.items():
        leaf = leaves[lid]
        total = cts[0]
        for extra in cts[1:]:
            total = total + extra
        req = leaf._grad_req
        if req == "null":
            continue
        if leaf._grad is None:
            leaf._grad = NDArray(jnp.zeros_like(leaf.data))
        if req == "write":
            leaf._grad._rebind(total.astype(leaf.data.dtype))
        elif req == "add":
            leaf._grad._rebind(leaf._grad.data + total.astype(leaf.data.dtype))


def _toposort(roots: List[_Node]) -> List[_Node]:
    """Reverse-topological order (outputs first) over the tape DAG."""
    visited = set()
    post = []
    # iterative DFS to survive deep chains (RNN tapes)
    for root in roots:
        if id(root) in visited:
            continue
        stack = [(root, iter(_parents(root)))]
        visited.add(id(root))
        while stack:
            node, it = stack[-1]
            advanced = False
            for p in it:
                if id(p) not in visited:
                    visited.add(id(p))
                    stack.append((p, iter(_parents(p))))
                    advanced = True
                    break
            if not advanced:
                post.append(node)
                stack.pop()
    post.reverse()  # outputs first
    return post


def _parents(node: _Node):
    for arr in node.inputs:
        if arr is not None and arr._ag is not None and arr._ag.node is not None:
            yield arr._ag.node


# ------------------------------------------------------------------ helpers
def mark_variables(variables, gradients, grad_reqs="write"):
    """Reference: ``autograd.mark_variables``."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad_req = req
        v._grad = g
        v._ag = _AGInfo(None)


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode=True):
    """Compute and RETURN grads of heads wrt variables (reference API)."""
    if create_graph:
        raise MXNetError("create_graph=True (higher order) not supported yet")
    variables = list(variables)
    saved = [(v._grad, v._grad_req, v._ag) for v in variables]
    for v in variables:
        if v._ag is None:
            raise MXNetError("variables must be tracked (attach_grad or used "
                             "as recorded outputs)")
        v._grad = NDArray(jnp.zeros_like(v.data))
        if v._grad_req == "null":
            v._grad_req = "write"
    heads = [heads] if isinstance(heads, NDArray) else list(heads)
    backward(heads, head_grads, retain_graph=bool(retain_graph))
    outs = [v._grad for v in variables]
    for v, (g, req, ag) in zip(variables, saved):
        v._grad, v._grad_req = g, req
    return outs


def get_symbol(x):  # legacy API stub
    raise MXNetError("the symbolic tape export has no TPU-native equivalent; "
                     "use HybridBlock.export instead")


class Function:
    """Custom differentiable function (reference: ``autograd.Function``).

    Subclass and implement ``forward(self, *inputs)`` and
    ``backward(self, *output_grads)`` operating on NDArrays.
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *arrays):
        self._saved = arrays

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):  # pragma: no cover - abstract
        raise NotImplementedError

    def backward(self, *output_grads):  # pragma: no cover - abstract
        raise NotImplementedError

    def __call__(self, *inputs):
        with pause():
            outputs = self.forward(*inputs)
        if not is_recording() or not any(_is_tracked(i) for i in inputs):
            return outputs
        multi = isinstance(outputs, (tuple, list))
        outs_t = tuple(outputs) if multi else (outputs,)

        func = self

        def vjp_fn(cts):
            cts_t = cts if isinstance(cts, (tuple, list)) else (cts,)
            with pause():
                in_grads = func.backward(*[NDArray(c) for c in cts_t])
            in_grads_t = in_grads if isinstance(in_grads, (tuple, list)) else (in_grads,)
            return tuple(
                g.data if isinstance(g, NDArray) else g for g in in_grads_t
            )

        avals = [(o.data.shape, o.data.dtype) for o in outs_t]
        node = _Node(vjp_fn, [a if _is_tracked(a) else None for a in inputs],
                     avals, multi)
        for i, o in enumerate(outs_t):
            _mark_output(o, node, i)
        return outputs
