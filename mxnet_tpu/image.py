"""Image API (reference: ``python/mxnet/image/image.py`` [unverified]):
decode/resize/augment pipeline + ``ImageIter``. Host-side numpy (cv2/PIL for
codecs when present); the batched output feeds the device once per batch."""

from __future__ import annotations

import os
import random as _pyrandom

import numpy as _np

from .base import MXNetError
from .ndarray.ndarray import NDArray
from .ndarray import array as nd_array
from .io import DataIter, DataBatch, DataDesc

__all__ = [
    "imread", "imdecode", "imresize", "resize_short", "fixed_crop",
    "center_crop", "random_crop", "random_size_crop", "color_normalize",
    "Augmenter", "ResizeAug", "ForceResizeAug", "RandomCropAug",
    "CenterCropAug", "HorizontalFlipAug", "ColorNormalizeAug", "CastAug",
    "BrightnessJitterAug", "ContrastJitterAug", "SaturationJitterAug",
    "ColorJitterAug", "LightingAug", "RandomOrderAug",
    "CreateAugmenter", "ImageIter",
]


def _to_np(img):
    return img.asnumpy() if isinstance(img, NDArray) else _np.asarray(img)


def imdecode(buf, flag=1, to_rgb=True, **kwargs):
    """Decode a jpeg/png byte buffer to an HWC NDArray (reference API)."""
    from .recordio import _decode_image

    img = _decode_image(bytes(buf), 1 if flag else 0)
    if img is None:
        raise MXNetError("image decode failed")
    if to_rgb and img.ndim == 3:
        img = img[..., ::-1]  # BGR (cv2 convention) -> RGB
    return nd_array(_np.ascontiguousarray(img))


def imread(filename, flag=1, to_rgb=True):
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src, w, h, interp=1):
    from .gluon.data.vision.transforms import _resize

    return nd_array(_resize(_to_np(src), (w, h), interp))


def resize_short(src, size, interp=2):
    img = _to_np(src)
    h, w = img.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp=1)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    img = _to_np(src)[y0 : y0 + h, x0 : x0 + w]
    if size is not None and (w, h) != size:
        return imresize(nd_array(img), size[0], size[1])
    return nd_array(img)


def center_crop(src, size, interp=2):
    img = _to_np(src)
    h, w = img.shape[:2]
    new_w, new_h = size
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_crop(src, size, interp=2):
    img = _to_np(src)
    h, w = img.shape[:2]
    new_w, new_h = size
    x0 = _pyrandom.randint(0, max(0, w - new_w))
    y0 = _pyrandom.randint(0, max(0, h - new_h))
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2, **kwargs):
    img = _to_np(src)
    h, w = img.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = _pyrandom.uniform(*area) * src_area
        log_ratio = (_np.log(ratio[0]), _np.log(ratio[1]))
        new_ratio = _np.exp(_pyrandom.uniform(*log_ratio))
        new_w = int(round(_np.sqrt(target_area * new_ratio)))
        new_h = int(round(_np.sqrt(target_area / new_ratio)))
        if new_w <= w and new_h <= h:
            x0 = _pyrandom.randint(0, w - new_w)
            y0 = _pyrandom.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    img = _to_np(src).astype("float32")
    img = img - _to_np(mean)
    if std is not None:
        img = img / _to_np(std)
    return nd_array(img)


class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):  # pragma: no cover - abstract
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            return nd_array(_np.ascontiguousarray(_to_np(src)[:, ::-1]))
        return src


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=list(_np.ravel(mean)), std=list(_np.ravel(std)))
        self.mean = _np.asarray(mean, "float32")
        self.std = _np.asarray(std, "float32")

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return nd_array(_to_np(src).astype(self.typ))


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.brightness, self.brightness)
        return nd_array(_to_np(src).astype("float32") * alpha)


class ContrastJitterAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], "float32")

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        img = _to_np(src).astype("float32")
        alpha = 1.0 + _pyrandom.uniform(-self.contrast, self.contrast)
        gray = (img * self._coef).sum() * 3.0 / img.size
        return nd_array(img * alpha + gray * (1 - alpha))


class SaturationJitterAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], "float32")

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        img = _to_np(src).astype("float32")
        alpha = 1.0 + _pyrandom.uniform(-self.saturation, self.saturation)
        gray = (img * self._coef).sum(axis=2, keepdims=True)
        return nd_array(img * alpha + gray * (1 - alpha))


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        ts = list(self.ts)
        _pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    def __init__(self, alphastd, eigval, eigvec):
        super().__init__()
        self.alphastd = alphastd
        self.eigval = _np.asarray(eigval, "float32")
        self.eigvec = _np.asarray(eigvec, "float32")

    def __call__(self, src):
        alpha = _np.random.normal(0, self.alphastd, size=(3,)).astype("float32")
        rgb = (self.eigvec * alpha * self.eigval).sum(axis=1)
        return nd_array(_to_np(src).astype("float32") + rgb)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Standard augmenter list builder (reference API)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        auglist.append(
            type("RandomSizedCropAug", (Augmenter,), {
                "__call__": lambda self, src: random_size_crop(
                    src, crop_size, (0.08, 1.0), (3 / 4.0, 4 / 3.0)
                )[0]
            })()
        )
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array(
            [[-0.5675, 0.7192, 0.4009],
             [-0.5808, -0.0045, -0.8140],
             [-0.5836, -0.6948, 0.4203]]
        )
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if mean is not None or std is not None:
        if mean is True:
            mean = _np.array([123.68, 116.28, 103.53])
        if std is True:
            std = _np.array([58.395, 57.12, 57.375])
        auglist.append(ColorNormalizeAug(mean, std if std is not None else 1.0))
    return auglist


class ImageIter(DataIter):
    """Image iterator over .rec shards or a path list (reference:
    ``mx.image.ImageIter`` over the C++ ImageRecordIter)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imglist=None, data_name="data", label_name="softmax_label",
                 dtype="float32", **kwargs):
        super().__init__(batch_size)
        assert path_imgrec or path_imglist or imglist, (
            "one of path_imgrec/path_imglist/imglist required"
        )
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        self.dtype = dtype
        self._data_name = data_name
        self._label_name = label_name
        self.auglist = aug_list if aug_list is not None else CreateAugmenter(
            data_shape
        )
        self.imgrec = None
        self.seq = None
        if path_imgrec:
            from .recordio import MXIndexedRecordIO

            idx_path = os.path.splitext(path_imgrec)[0] + ".idx"
            self.imgrec = MXIndexedRecordIO(idx_path, path_imgrec, "r")
            self.seq = list(self.imgrec.keys)
        else:
            entries = []
            if path_imglist:
                with open(path_imglist) as fin:
                    for line in fin:
                        parts = line.strip().split("\t")
                        label = _np.array(
                            [float(i) for i in parts[1:-1]], "float32"
                        )
                        entries.append((parts[-1], label))
            else:
                for item in imglist:
                    entries.append((item[-1], _np.asarray(item[:-1], "float32")))
            self.imglist = entries
            self.path_root = path_root
            self.seq = list(range(len(entries)))
        # sharded reading (reference: part_index/num_parts)
        n = len(self.seq)
        per = n // num_parts
        self.seq = self.seq[part_index * per : (part_index + 1) * per] \
            if num_parts > 1 else self.seq
        self.cur = 0
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self._data_name, (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [DataDesc(self._label_name, (self.batch_size, self.label_width)
                         if self.label_width > 1 else (self.batch_size,))]

    def reset(self):
        if self.shuffle:
            _pyrandom.shuffle(self.seq)
        self.cur = 0

    def _read_sample(self, idx):
        if self.imgrec is not None:
            from .recordio import unpack

            header, img_bytes = unpack(self.imgrec.read_idx(idx))
            label = header.label
            img = imdecode(img_bytes)
        else:
            fname, label = self.imglist[idx]
            img = imread(os.path.join(self.path_root, fname))
        return img, label

    def next(self):
        if self.cur + self.batch_size > len(self.seq):
            raise StopIteration
        c, h, w = self.data_shape
        data = _np.zeros((self.batch_size, c, h, w), self.dtype)
        labels = _np.zeros(
            (self.batch_size, self.label_width), "float32"
        )
        for i in range(self.batch_size):
            img, label = self._read_sample(self.seq[self.cur + i])
            for aug in self.auglist:
                img = aug(img)
            arr = _to_np(img)
            if arr.ndim == 2:
                arr = arr[:, :, None]
            data[i] = arr.transpose(2, 0, 1)
            labels[i] = _np.ravel(label)[: self.label_width]
        self.cur += self.batch_size
        label_out = labels if self.label_width > 1 else labels[:, 0]
        return DataBatch(
            data=[nd_array(data)], label=[nd_array(label_out)], pad=0,
        )
