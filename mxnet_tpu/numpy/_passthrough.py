"""Shared factory for numpy-namespace passthrough wrappers.

One generator used by ``mx.np``, ``mx.np.linalg`` and ``mx.np.fft``: wraps a
``jax.numpy``-level function so positional args are treated as (potential)
arrays dispatched through the imperative invoke path (autograd-recorded,
jit-traceable) and keyword args as static parameters.
"""

from __future__ import annotations


def make_wrapper(jfn, prefix: str):
    def fn(*args, **kwargs):
        from ..imperative import invoke_fn

        return invoke_fn(lambda *xs: jfn(*xs, **kwargs), *args)

    fn.__name__ = jfn.__name__
    fn.__qualname__ = jfn.__name__
    fn.__doc__ = f"{prefix}.{jfn.__name__} — numpy-semantics wrapper over the jax equivalent."
    return fn


def install(module, source, names, prefix: str):
    """Install wrappers for every ``names`` entry present on ``source``."""
    installed = []
    seen = set()
    for name in names:
        if name in seen or not hasattr(source, name):
            continue
        seen.add(name)
        setattr(module, name, make_wrapper(getattr(source, name), prefix))
        installed.append(name)
    return installed
