"""Shared factory for numpy-namespace passthrough wrappers.

One generator used by ``mx.np``, ``mx.np.linalg`` and ``mx.np.fft``: wraps a
``jax.numpy``-level function so positional args are treated as (potential)
arrays dispatched through the imperative invoke path (autograd-recorded,
jit-traceable) and keyword args as static parameters.
"""

from __future__ import annotations


def _is_arraylike(a):
    # NDArray / jax / numpy arrays and numpy scalars — things autograd can
    # track or jax can differentiate; plain python ints/tuples (axis, shape,
    # split points) must stay STATIC or they get vjp-traced under record()
    return hasattr(a, "shape") and hasattr(a, "dtype")


def make_wrapper(jfn, prefix: str):
    def fn(*args, **kwargs):
        from ..imperative import invoke_fn

        # expand one level of list/tuple-of-arrays (stack, concatenate,
        # vstack, ...) so each element dispatches as its own operand —
        # autograd then records/propagates per element; non-array
        # positionals (axis ints etc.) are closed over statically
        spec = []
        flat = []
        statics = []
        for a in args:
            if isinstance(a, (list, tuple)) and a and all(
                _is_arraylike(x) for x in a
            ):
                spec.append(len(a))
                flat.extend(a)
            elif _is_arraylike(a):
                spec.append("arr")
                flat.append(a)
            else:
                # python scalars, axis ints, shape tuples, None, strings:
                # closed over statically (they never carry gradients, and
                # a traced positional axis breaks jnp under record())
                spec.append(None)
                statics.append(a)

        def call(*xs):
            it = iter(xs)
            st = iter(statics)
            rebuilt = []
            for s in spec:
                if s is None:
                    rebuilt.append(next(st))
                elif s == "arr":
                    rebuilt.append(next(it))
                else:
                    rebuilt.append([next(it) for _ in range(s)])
            return jfn(*rebuilt, **kwargs)

        return invoke_fn(call, *flat)

    fn.__name__ = jfn.__name__
    fn.__qualname__ = jfn.__name__
    fn.__doc__ = f"{prefix}.{jfn.__name__} — numpy-semantics wrapper over the jax equivalent."
    return fn


def install(module, source, names, prefix: str):
    """Install wrappers for every ``names`` entry present on ``source``."""
    installed = []
    seen = set()
    for name in names:
        if name in seen or not hasattr(source, name):
            continue
        seen.add(name)
        setattr(module, name, make_wrapper(getattr(source, name), prefix))
        installed.append(name)
    return installed
