"""``mx.np.fft`` over ``jnp.fft``."""

from __future__ import annotations

import sys

import jax.numpy as jnp

from ._passthrough import install as _install

_FUNCS = ["fft", "ifft", "rfft", "irfft", "fft2", "ifft2", "fftn", "ifftn",
          "fftshift", "ifftshift", "fftfreq", "rfftfreq"]

_install(sys.modules[__name__], jnp.fft, _FUNCS, "mx.np.fft")
