"""``mx.np.random``: numpy-style random sampling over the stateful key."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ndarray.ndarray import NDArray, _unwrap
from ..random import next_key, seed  # re-export seed

__all__ = ["seed", "uniform", "normal", "randn", "rand", "randint", "choice",
           "shuffle", "permutation", "beta", "gamma", "exponential", "chisquare",
           "multinomial", "bernoulli", "laplace", "gumbel", "logistic", "pareto",
           "power", "rayleigh", "weibull", "lognormal", "multivariate_normal"]


def _shape(size):
    if size is None:
        return ()
    return (size,) if isinstance(size, int) else tuple(size)


def uniform(low=0.0, high=1.0, size=None, dtype=None, ctx=None, device=None, out=None):
    data = jax.random.uniform(next_key(), _shape(size), minval=low, maxval=high,
                              dtype=jnp.dtype(dtype) if dtype else jnp.float32)
    return NDArray(data)


def normal(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None, device=None, out=None):
    data = loc + scale * jax.random.normal(next_key(), _shape(size),
                                           dtype=jnp.dtype(dtype) if dtype else jnp.float32)
    return NDArray(data)


def randn(*size):
    return normal(0.0, 1.0, size or None)


def rand(*size):
    return uniform(0.0, 1.0, size or None)


def randint(low, high=None, size=None, dtype="int32", ctx=None, device=None):
    if high is None:
        low, high = 0, low
    return NDArray(jax.random.randint(next_key(), _shape(size), low, high,
                                      dtype=jnp.dtype(dtype)))


def choice(a, size=None, replace=True, p=None, ctx=None, device=None):
    arr = jnp.arange(a) if isinstance(a, int) else _unwrap(a)
    pd = _unwrap(p) if p is not None else None
    return NDArray(jax.random.choice(next_key(), arr, _shape(size), replace=replace, p=pd))


def shuffle(x):
    x._rebind(jax.random.permutation(next_key(), x.data, axis=0))


def permutation(x):
    if isinstance(x, int):
        return NDArray(jax.random.permutation(next_key(), x))
    return NDArray(jax.random.permutation(next_key(), _unwrap(x), axis=0))


def beta(a, b, size=None):
    return NDArray(jax.random.beta(next_key(), a, b, _shape(size)))


def gamma(shape, scale=1.0, size=None):
    return NDArray(jax.random.gamma(next_key(), shape, _shape(size)) * scale)


def exponential(scale=1.0, size=None):
    return NDArray(scale * jax.random.exponential(next_key(), _shape(size)))


def chisquare(df, size=None):
    return NDArray(2.0 * jax.random.gamma(next_key(), df / 2.0, _shape(size)))


def multinomial(n, pvals, size=None):
    p = _unwrap(pvals)
    counts = jax.random.multinomial(next_key(), n, p, shape=_shape(size) or None)
    return NDArray(counts)


def bernoulli(prob, size=None, dtype="float32"):
    return NDArray(jax.random.bernoulli(next_key(), _unwrap(prob), _shape(size) or None)
                   .astype(jnp.dtype(dtype)))


def laplace(loc=0.0, scale=1.0, size=None):
    return NDArray(loc + scale * jax.random.laplace(next_key(), _shape(size)))


def gumbel(loc=0.0, scale=1.0, size=None):
    return NDArray(loc + scale * jax.random.gumbel(next_key(), _shape(size)))


def logistic(loc=0.0, scale=1.0, size=None):
    return NDArray(loc + scale * jax.random.logistic(next_key(), _shape(size)))


def pareto(a, size=None):
    return NDArray(jax.random.pareto(next_key(), a, _shape(size)) - 1.0)


def power(a, size=None):
    u = jax.random.uniform(next_key(), _shape(size))
    return NDArray(jnp.power(u, 1.0 / a))


def rayleigh(scale=1.0, size=None):
    u = jax.random.uniform(next_key(), _shape(size), minval=1e-12)
    return NDArray(scale * jnp.sqrt(-2.0 * jnp.log(u)))


def weibull(a, size=None):
    u = jax.random.uniform(next_key(), _shape(size), minval=1e-12)
    return NDArray(jnp.power(-jnp.log(u), 1.0 / a))


def lognormal(mean=0.0, sigma=1.0, size=None):
    return NDArray(jnp.exp(mean + sigma * jax.random.normal(next_key(), _shape(size))))


def multivariate_normal(mean, cov, size=None):
    return NDArray(jax.random.multivariate_normal(
        next_key(), _unwrap(mean), _unwrap(cov), _shape(size) or None))
