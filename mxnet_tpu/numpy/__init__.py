"""``mx.np``: NumPy-semantics array API.

Reference: ``python/mxnet/numpy/`` (multiarray.py) [unverified] — the 2.0-era
NumPy-compatible surface GluonNLP models use. Here every function wraps the
corresponding ``jax.numpy`` function through the imperative invoke path, so
autograd records it and ``hybridize()`` traces it; the array type is the same
NDArray as ``mx.nd`` (the reference kept two array classes; one suffices when
both namespaces share one functional backend).
"""

from __future__ import annotations

import sys

import jax
import jax.numpy as jnp
import numpy as _onp

from ..context import Context
from ..ndarray.ndarray import NDArray, _unwrap

ndarray = NDArray

# constants / dtypes re-exported for API parity
pi = _onp.pi
e = _onp.e
inf = _onp.inf
nan = _onp.nan
newaxis = None
float16 = _onp.float16
float32 = _onp.float32
float64 = _onp.float64
bfloat16 = jnp.bfloat16
int8 = _onp.int8
int32 = _onp.int32
int64 = _onp.int64
uint8 = _onp.uint8
bool_ = _onp.bool_

_f32 = jnp.float32


def _invoke(fn, *args, **static):
    from ..imperative import invoke_fn

    return invoke_fn(fn, *args, **static)


def array(obj, dtype=None, ctx=None, device=None) -> NDArray:
    from ..ndarray.ndarray import array as _array

    return _array(obj, ctx=ctx or device, dtype=dtype)


def _creation(jfn):
    def fn(*args, ctx=None, device=None, dtype=None, **kw):
        out = jfn(*args, **({"dtype": jnp.dtype(dtype)} if dtype else {}), **kw)
        if out.dtype == jnp.float64:
            out = out.astype(_f32)
        return NDArray(out, ctx=ctx or device)

    fn.__name__ = jfn.__name__
    return fn


zeros = _creation(jnp.zeros)
ones = _creation(jnp.ones)
empty = _creation(jnp.zeros)
full = _creation(jnp.full)
arange = _creation(jnp.arange)
linspace = _creation(jnp.linspace)
logspace = _creation(jnp.logspace)
eye = _creation(jnp.eye)
identity = _creation(jnp.identity)
tri = _creation(jnp.tri)


def zeros_like(a, dtype=None, **kw):
    return _invoke(lambda d: jnp.zeros_like(d, dtype=jnp.dtype(dtype) if dtype else None), a)


def ones_like(a, dtype=None, **kw):
    return _invoke(lambda d: jnp.ones_like(d, dtype=jnp.dtype(dtype) if dtype else None), a)


def full_like(a, fill_value, dtype=None, **kw):
    return _invoke(
        lambda d: jnp.full_like(d, fill_value, dtype=jnp.dtype(dtype) if dtype else None), a
    )


# array-consuming jnp functions exposed verbatim; positional args are treated
# as (potential) arrays, keyword args as static parameters.
_PASSTHROUGH = [
    # elementwise
    "add", "subtract", "multiply", "divide", "true_divide", "floor_divide",
    "mod", "remainder", "power", "float_power", "negative", "positive", "absolute",
    "abs", "fabs", "sign", "rint", "round", "floor", "ceil", "trunc",
    "sqrt", "cbrt", "square", "reciprocal", "exp", "expm1", "exp2", "log",
    "log2", "log10", "log1p", "sin", "cos", "tan", "arcsin", "arccos", "arctan",
    "arctan2", "sinh", "cosh", "tanh", "arcsinh", "arccosh", "arctanh",
    "hypot", "degrees", "radians", "deg2rad", "rad2deg", "maximum", "minimum",
    "fmax", "fmin", "clip", "logaddexp", "logaddexp2", "copysign", "nextafter",
    "ldexp", "heaviside", "gcd", "lcm",
    # logic
    "logical_and", "logical_or", "logical_xor", "logical_not", "equal",
    "not_equal", "greater", "greater_equal", "less", "less_equal", "isnan",
    "isinf", "isfinite", "isposinf", "isneginf", "isclose", "array_equal",
    "signbit",
    # reductions
    "sum", "prod", "mean", "std", "var", "min", "max", "amin", "amax", "ptp",
    "median", "average", "nansum", "nanprod", "nanmean", "nanstd", "nanvar",
    "nanmin", "nanmax", "cumsum", "cumprod", "nancumsum", "all", "any",
    "count_nonzero", "argmax", "argmin", "nanargmax", "nanargmin",
    # shape
    "reshape", "ravel", "transpose", "swapaxes", "moveaxis", "rollaxis",
    "expand_dims", "squeeze", "broadcast_to", "broadcast_arrays", "atleast_1d",
    "atleast_2d", "atleast_3d", "concatenate", "stack", "vstack", "hstack",
    "dstack", "column_stack", "split", "array_split", "vsplit", "hsplit",
    "dsplit", "tile", "repeat", "flip", "fliplr", "flipud", "roll", "rot90",
    "pad", "append", "delete", "insert", "resize", "trim_zeros", "flatnonzero",
    # indexing / selection
    "take", "take_along_axis", "choose", "compress", "diag", "diagonal",
    "diagflat", "tril", "triu", "where", "extract", "searchsorted", "nonzero",
    "argwhere", "unravel_index", "ravel_multi_index", "ix_", "indices",
    "select", "piecewise", "putmask",
    # sorting
    "sort", "argsort", "lexsort", "partition", "argpartition", "unique",
    # linalg-ish
    "dot", "vdot", "inner", "outer", "matmul", "tensordot", "einsum", "kron",
    "cross", "trace",
    # other
    "interp", "convolve", "correlate", "diff", "ediff1d", "gradient",
    "histogram", "bincount", "digitize", "corrcoef", "cov", "floor_divide",
    "angle", "real", "imag", "conj", "conjugate", "i0", "sinc", "nan_to_num",
    "meshgrid", "apply_along_axis", "apply_over_axes",
]

from ._passthrough import install as _install_passthrough

_install_passthrough(sys.modules[__name__], jnp, _PASSTHROUGH, "mx.np")


def asarray(obj, dtype=None):
    return array(obj, dtype=dtype)


def ascontiguousarray(obj, dtype=None):
    return array(obj, dtype=dtype)


def copy(a):
    return NDArray(jnp.array(_unwrap(a)))


def shape(a):
    return tuple(_unwrap(a).shape)


def ndim(a):
    return _unwrap(a).ndim


def size(a):
    return int(_unwrap(a).size)


def may_share_memory(a, b):
    return False


def shares_memory(a, b):
    return False


def dtype(d):
    return _onp.dtype(d)


def result_type(*args):
    return _onp.result_type(*[(_unwrap(a).dtype if isinstance(a, NDArray) else a) for a in args])


def can_cast(from_, to):
    return _onp.can_cast(from_, to)


def issubdtype(a, b):
    return _onp.issubdtype(a, b)


from . import linalg  # noqa: E402
from . import random  # noqa: E402
from . import fft  # noqa: E402
