"""``mx.np.linalg`` over ``jnp.linalg`` (reference: mxnet.numpy.linalg)."""

from __future__ import annotations

import sys

import jax.numpy as jnp

from ._passthrough import install as _install

_FUNCS = [
    "norm", "svd", "cholesky", "qr", "inv", "pinv", "det", "slogdet", "eig",
    "eigh", "eigvals", "eigvalsh", "solve", "lstsq", "matrix_rank",
    "matrix_power", "multi_dot", "tensorinv", "tensorsolve",
]

_install(sys.modules[__name__], jnp.linalg, _FUNCS, "mx.np.linalg")
