"""Sparse NDArray storage: ``row_sparse`` and ``csr`` types.

Reference: ``src/ndarray/`` row_sparse/CSR storage + ``src/operator/tensor``
sparse kernels [unverified]. On TPU, XLA has no sparse buffer type and the
MXU wants dense tiles, so the stance is split by role:

- general sparse COMPUTE (csr dot etc.) keeps the API with dense backing —
  the facade role;
- the sparse TRAINING path is real: ``RowSparseNDArray.from_pair`` holds a
  compressed (rows, vals) pair on device, produced by
  ``Embedding(sparse_grad=True)`` backward, consumed by the lazy sparse
  SGD/Adam updates (scatter to live rows only) and by
  ``kvstore.row_sparse_pull`` (gather of requested rows) — the cases the
  reference actually optimized with row_sparse kernels.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from .ndarray import NDArray, _unwrap

__all__ = [
    "BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
    "row_sparse_array", "csr_matrix", "zeros",
]


class BaseSparseNDArray(NDArray):
    """Common base; behaves as a dense NDArray with sparse metadata."""

    _stype = "default"

    @property
    def stype(self):
        return self._stype

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self.data)
        if stype == self._stype:
            return self
        raise MXNetError(f"cannot convert {self._stype} to {stype}")


class RowSparseNDArray(BaseSparseNDArray):
    """Row-sparse array. Two storage modes:

    - dense-backed (the round-2 facade): behaves as dense, indices/values
      derived by scanning;
    - PAIR-backed (``from_pair``): holds a compressed (rows, vals) pair —
      the REAL sparse storage used by sparse embedding gradients, sparse
      optimizer updates, and ``kvstore.row_sparse_pull``. The dense view
      is scatter-materialized lazily only if some consumer asks for
      ``.data``; the sparse training path never does.
    """

    _stype = "row_sparse"

    @classmethod
    def from_pair(cls, rows, vals, shape) -> "RowSparseNDArray":
        """rows (K,) int32 (duplicates allowed — they SUM on densify,
        gradient semantics), vals (K, ...) matching shape[1:]."""
        obj = cls.__new__(cls)
        NDArray.__init__(obj, jnp.zeros((), jnp.float32))  # placeholder
        obj._rs_rows = jnp.asarray(_unwrap(rows)).astype(jnp.int32)
        obj._rs_vals = jnp.asarray(_unwrap(vals))
        obj._rs_shape = tuple(shape)
        obj._rs_dense = None
        return obj

    @property
    def _pair(self):
        return getattr(self, "_rs_rows", None) is not None \
            and getattr(self, "_rs_shape", None) is not None

    def _rebind(self, new_data):
        # writing a dense value into a pair-backed array (kvstore.pull
        # into a grad buffer) must DROP the stale pair, or .data keeps
        # returning the old compressed value
        if getattr(self, "_rs_shape", None) is not None:
            self._rs_rows = None
            self._rs_vals = None
            self._rs_shape = None
            self._rs_dense = None
        NDArray._rebind(self, new_data)

    # ------------------------------------------------- dense materialization
    @property
    def data(self):
        if getattr(self, "_rs_shape", None) is not None:
            if self._rs_dense is None:
                dense = jnp.zeros(self._rs_shape, self._rs_vals.dtype)
                self._rs_dense = dense.at[self._rs_rows].add(self._rs_vals)
            return self._rs_dense
        return NDArray.data.fget(self)

    @property
    def shape(self):
        if getattr(self, "_rs_shape", None) is not None:
            return self._rs_shape
        return NDArray.shape.fget(self)

    @property
    def indices(self) -> NDArray:
        if self._pair:
            return NDArray(self._rs_rows)
        nz = _np.nonzero(_np.any(self.asnumpy() != 0, axis=tuple(range(1, self.ndim))))[0]
        return NDArray(jnp.asarray(nz, jnp.int32))

    @property
    def values(self) -> NDArray:  # data rows at indices
        if self._pair:
            return NDArray(self._rs_vals)
        return NDArray(jnp.take(self.data, self.indices.data.astype(jnp.int32), axis=0))

    def __add__(self, other):
        # pair + pair concatenates (gradient accumulation keeps compressed)
        if self._pair and isinstance(other, RowSparseNDArray) and other._pair:
            assert self._rs_shape == other._rs_shape
            return RowSparseNDArray.from_pair(
                jnp.concatenate([self._rs_rows, other._rs_rows]),
                jnp.concatenate([self._rs_vals, other._rs_vals]),
                self._rs_shape,
            )
        return NDArray.__add__(self, other)

    def retain(self, indices) -> "RowSparseNDArray":
        idx = jnp.asarray(_unwrap(indices)).astype(jnp.int32)
        if self._pair:
            # reference retain REMOVES non-retained rows (indices shrink);
            # eager-only path, so the dynamic result shape is fine
            keep = _np.asarray(jnp.isin(self._rs_rows, idx))
            return RowSparseNDArray.from_pair(
                self._rs_rows[keep], self._rs_vals[keep], self._rs_shape
            )
        keep = jnp.zeros((self.shape[0],), bool).at[idx].set(True)
        out = jnp.where(keep.reshape((-1,) + (1,) * (self.ndim - 1)), self.data, 0)
        return RowSparseNDArray(out)


class CSRNDArray(BaseSparseNDArray):
    _stype = "csr"

    @property
    def indptr(self) -> NDArray:
        a = self.asnumpy()
        counts = (a != 0).sum(axis=1)
        return NDArray(jnp.asarray(_np.concatenate([[0], _np.cumsum(counts)]), jnp.int32))

    @property
    def indices(self) -> NDArray:
        a = self.asnumpy()
        return NDArray(jnp.asarray(_np.nonzero(a)[1], jnp.int32))

    @property
    def values(self) -> NDArray:
        a = self.asnumpy()
        return NDArray(jnp.asarray(a[a != 0]))


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None) -> RowSparseNDArray:
    if isinstance(arg1, tuple) and len(arg1) == 2 and not _np.isscalar(arg1[0]):
        values, indices = arg1
        values = _unwrap(values)
        idx = _np.asarray(_unwrap(indices)).astype(_np.int32)
        full_shape = shape or ((int(idx.max()) + 1,) + tuple(values.shape[1:]))
        dense = jnp.zeros(full_shape, values.dtype if dtype is None else jnp.dtype(dtype))
        dense = dense.at[idx].set(values)
        return RowSparseNDArray(dense, ctx=ctx)
    return RowSparseNDArray(jnp.asarray(_unwrap(arg1)), ctx=ctx)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None) -> CSRNDArray:
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = (_np.asarray(_unwrap(a)) for a in arg1)
        n_rows = len(indptr) - 1
        n_cols = shape[1] if shape else int(indices.max()) + 1
        dense = _np.zeros((n_rows, n_cols), dtype=data.dtype if dtype is None else dtype)
        for r in range(n_rows):
            cols = indices[indptr[r]:indptr[r + 1]].astype(int)
            dense[r, cols] = data[indptr[r]:indptr[r + 1]]
        return CSRNDArray(jnp.asarray(dense), ctx=ctx)
    return CSRNDArray(jnp.asarray(_unwrap(arg1)), ctx=ctx)


def zeros(stype, shape, ctx=None, dtype=None):
    import jax.numpy as jnp

    cls = {"row_sparse": RowSparseNDArray, "csr": CSRNDArray, "default": NDArray}[stype]
    return cls(jnp.zeros(shape, jnp.dtype(dtype) if dtype else jnp.float32), ctx=ctx)
