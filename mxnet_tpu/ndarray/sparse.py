"""Sparse NDArray facade: ``row_sparse`` and ``csr`` storage types.

Reference: ``src/ndarray/`` row_sparse/CSR storage + ``src/operator/tensor``
sparse kernels [unverified]. On TPU, XLA has no sparse buffer type and the
MXU wants dense tiles, so the TPU-native stance is: keep the *API* (creation,
``.indices``/``.data``, conversion, sparse ``dot``) while backing storage
densely the moment it reaches device; ``row_sparse`` keeps its compressed
(indices, values) host-side identity for the cases the reference optimized
(embedding gradients, kvstore push), which our Trainer handles by scatter-add
on device instead.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from .ndarray import NDArray, _unwrap

__all__ = [
    "BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
    "row_sparse_array", "csr_matrix", "zeros",
]


class BaseSparseNDArray(NDArray):
    """Common base; behaves as a dense NDArray with sparse metadata."""

    _stype = "default"

    @property
    def stype(self):
        return self._stype

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self.data)
        if stype == self._stype:
            return self
        raise MXNetError(f"cannot convert {self._stype} to {stype}")


class RowSparseNDArray(BaseSparseNDArray):
    _stype = "row_sparse"

    @property
    def indices(self) -> NDArray:
        nz = _np.nonzero(_np.any(self.asnumpy() != 0, axis=tuple(range(1, self.ndim))))[0]
        return NDArray(jnp.asarray(nz, jnp.int32))

    @property
    def values(self) -> NDArray:  # data rows at indices
        return NDArray(jnp.take(self.data, self.indices.data.astype(jnp.int32), axis=0))

    def retain(self, indices) -> "RowSparseNDArray":
        idx = jnp.asarray(_unwrap(indices)).astype(jnp.int32)
        keep = jnp.zeros((self.shape[0],), bool).at[idx].set(True)
        out = jnp.where(keep.reshape((-1,) + (1,) * (self.ndim - 1)), self.data, 0)
        return RowSparseNDArray(out)


class CSRNDArray(BaseSparseNDArray):
    _stype = "csr"

    @property
    def indptr(self) -> NDArray:
        a = self.asnumpy()
        counts = (a != 0).sum(axis=1)
        return NDArray(jnp.asarray(_np.concatenate([[0], _np.cumsum(counts)]), jnp.int32))

    @property
    def indices(self) -> NDArray:
        a = self.asnumpy()
        return NDArray(jnp.asarray(_np.nonzero(a)[1], jnp.int32))

    @property
    def values(self) -> NDArray:
        a = self.asnumpy()
        return NDArray(jnp.asarray(a[a != 0]))


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None) -> RowSparseNDArray:
    if isinstance(arg1, tuple) and len(arg1) == 2 and not _np.isscalar(arg1[0]):
        values, indices = arg1
        values = _unwrap(values)
        idx = _np.asarray(_unwrap(indices)).astype(_np.int32)
        full_shape = shape or ((int(idx.max()) + 1,) + tuple(values.shape[1:]))
        dense = jnp.zeros(full_shape, values.dtype if dtype is None else jnp.dtype(dtype))
        dense = dense.at[idx].set(values)
        return RowSparseNDArray(dense, ctx=ctx)
    return RowSparseNDArray(jnp.asarray(_unwrap(arg1)), ctx=ctx)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None) -> CSRNDArray:
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = (_np.asarray(_unwrap(a)) for a in arg1)
        n_rows = len(indptr) - 1
        n_cols = shape[1] if shape else int(indices.max()) + 1
        dense = _np.zeros((n_rows, n_cols), dtype=data.dtype if dtype is None else dtype)
        for r in range(n_rows):
            cols = indices[indptr[r]:indptr[r + 1]].astype(int)
            dense[r, cols] = data[indptr[r]:indptr[r + 1]]
        return CSRNDArray(jnp.asarray(dense), ctx=ctx)
    return CSRNDArray(jnp.asarray(_unwrap(arg1)), ctx=ctx)


def zeros(stype, shape, ctx=None, dtype=None):
    import jax.numpy as jnp

    cls = {"row_sparse": RowSparseNDArray, "csr": CSRNDArray, "default": NDArray}[stype]
    return cls(jnp.zeros(shape, jnp.dtype(dtype) if dtype else jnp.float32), ctx=ctx)
