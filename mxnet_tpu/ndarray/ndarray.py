"""NDArray: a mutable, asynchronous n-dimensional array over ``jax.Array``.

TPU-native analogue of the reference's ``NDArray``
(``src/ndarray/ndarray.cc``, ``include/mxnet/ndarray.h`` [unverified]).

The reference NDArray is a *mutable* buffer with in-place ops, storage-sharing
views, and engine-managed async readiness. ``jax.Array`` is immutable and
functional. The bridge (the "mutability shim", SURVEY.md section 7):

- Each root NDArray owns a ``_Chunk`` holding the current ``jax.Array`` plus a
  version counter. In-place ops REBIND the chunk to a new functional value
  (copy-on-write at the XLA level; buffer reuse comes from XLA donation on the
  jitted paths, mirroring the reference's ``static_alloc``).
- Views (``Slice``/``Reshape`` in the reference share storage) hold a parent
  reference plus an index/shape. Reads recompute lazily from the parent (and
  are cached against the root chunk's version); writes propagate back through
  the parent chain via lazy scatter (``.at[idx].set``), so aliasing semantics
  match the reference: writing through a view is visible in the base and in
  sibling views. Under ``autograd.record()`` slicing/reshaping of tracked
  arrays instead dispatches as a recorded op (no aliasing), matching the
  reference's restriction on differentiating through in-place writes.
- Asynchrony: jax dispatch is async by nature; ``wait_to_read`` blocks like
  the reference's ``Engine::WaitForVar``, ``asnumpy()`` is the sync point.

Autograd state (``_ag``) is attached by ``mxnet_tpu.autograd`` — the analogue
of the per-entry ``AGInfo`` in ``src/imperative/imperative.cc`` [unverified].
"""

from __future__ import annotations

import functools
import operator
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from ..context import Context, current_context
from ..engine import engine

__all__ = ["NDArray", "array", "empty", "from_jax", "waitall"]

_DEFAULT_DTYPE = jnp.float32


class _Chunk:
    """Rebindable storage cell (reference: ``NDArray::Chunk``)."""

    __slots__ = ("data", "version")

    def __init__(self, data: jax.Array):
        self.data = data
        self.version = 0

    def rebind(self, data: jax.Array):
        self.data = data
        self.version += 1


class _Pending:
    """Placeholder value for an op output still waiting in the forward
    bulk queue (imperative._BulkQueue): carries the aval so shape/dtype
    peeks don't force execution; reading ``.data`` flushes the queue.
    The reference's analogue is an engine var not yet written
    (``Engine::WaitForVar`` blocks on read; SURVEY §3.1)."""

    __slots__ = ("queue", "shape", "dtype", "weak_type", "value", "error")

    def __init__(self, queue, shape, dtype, weak_type=False):
        self.queue = queue
        self.shape = tuple(shape)
        self.dtype = dtype
        self.weak_type = weak_type  # promotion semantics survive the queue
        self.value = None  # concrete array, set by flush()
        self.error = None  # producing-op exception, if the flush failed


class _View:
    """View descriptor: how to derive this array from its parent."""

    __slots__ = ("parent", "kind", "index", "shape")

    def __init__(self, parent: "NDArray", kind: str, index=None, shape=None):
        self.parent = parent
        self.kind = kind  # 'slice' | 'reshape'
        self.index = index
        self.shape = shape


def _unwrap(x):
    return x.data if isinstance(x, NDArray) else x


def _unwrap_index(idx):
    if isinstance(idx, tuple):
        return tuple(_unwrap_index(i) for i in idx)
    if isinstance(idx, NDArray):
        d = idx.data
        return d.astype(jnp.int32) if jnp.issubdtype(d.dtype, jnp.floating) else d
    if isinstance(idx, list):
        return jnp.asarray(idx)
    return idx


def _invoke(fn, *args, **static):
    from ..imperative import invoke_fn

    return invoke_fn(fn, *args, **static)


def _recording_tracked(arr) -> bool:
    from .. import autograd

    return autograd.is_recording() and autograd._is_tracked(arr)


def _check_inplace_ok(arr):
    """In-place mutation of an array participating in a recorded graph would
    silently desync the tape's captured residuals from the visible value, so
    raise like the reference does (version-counter check in the engine)."""
    if _recording_tracked(arr):
        from ..base import MXNetError

        raise MXNetError(
            "in-place operations on arrays that are part of a recorded "
            "computation are not supported inside autograd.record(); use "
            "functional ops or mutate outside the record scope"
        )


class NDArray:
    """Mutable array handle. See module docstring for the storage model."""

    __array_priority__ = 1000.0

    __slots__ = (
        "_chunk",
        "_view",
        "_root",
        "_cache",
        "_cache_version",
        "_ag",
        "_grad",
        "_grad_req",
        "__weakref__",
    )

    # ------------------------------------------------------------------ init
    def __init__(self, data, ctx: Optional[Context] = None, _view: Optional[_View] = None):
        self._view = _view
        self._cache = None
        self._cache_version = -1
        self._ag = None
        self._grad = None
        self._grad_req = "null"
        if _view is not None:
            self._chunk = None
            self._root = _view.parent._root_array()
        else:
            if not isinstance(data, jax.Array) and type(data) is not _Pending:
                data = jnp.asarray(data)
            if ctx is not None:
                data = jax.device_put(data, ctx.jax_device())
            self._chunk = _Chunk(data)
            self._root = None

    def _root_array(self) -> "NDArray":
        return self._root if self._root is not None else self

    # ------------------------------------------------------------ data cell
    @property
    def data(self) -> jax.Array:
        """Current functional value of this array (lazy for views);
        forces the forward bulk queue when the value is still pending."""
        if self._view is None:
            d = self._chunk.data
            if type(d) is _Pending:
                if d.value is None:
                    d.queue.flush()
                if d.value is None:
                    # the producing op failed during flush; surface ITS
                    # error here instead of storing None and crashing
                    # somewhere unrelated later
                    err = d.error or MXNetError(
                        "bulk-queued op failed to produce this value")
                    raise err
                d = d.value
                self._chunk.data = d
            return d
        root = self._root_array()
        if self._cache is not None and self._cache_version == root._chunk.version:
            return self._cache
        v = self._view
        pdata = v.parent.data
        if v.kind == "slice":
            out = pdata[v.index]
        elif v.kind == "reshape":
            out = pdata.reshape(v.shape)
        else:  # pragma: no cover
            raise MXNetError(f"unknown view kind {v.kind}")
        self._cache = out
        self._cache_version = root._chunk.version
        return out

    def _rebind(self, new_data: jax.Array):
        """Point this array at a new value; views write back to their parent."""
        if self._view is None:
            self._chunk.rebind(new_data)
        else:
            v = self._view
            if v.kind == "slice":
                v.parent._rebind(v.parent.data.at[v.index].set(new_data))
            elif v.kind == "reshape":
                v.parent._rebind(jnp.reshape(new_data, v.parent.shape))
            self._cache = None
        engine().on_outputs([new_data])

    # ------------------------------------------------------------ properties
    @property
    def shape(self) -> Tuple[int, ...]:
        if self._view is None:
            return tuple(self._chunk.data.shape)  # peeks _Pending avals
        return tuple(self.data.shape)

    @property
    def dtype(self):
        if self._view is None:
            return _np.dtype(str(self._chunk.data.dtype))
        return _np.dtype(str(self.data.dtype))

    @property
    def size(self) -> int:
        return int(functools.reduce(operator.mul, self.shape, 1))

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def ctx(self) -> Context:
        if self._view is None and type(self._chunk.data) is _Pending:
            return current_context()  # placement resolves at flush
        d = self.data
        try:
            dev = next(iter(d.devices()))
        except Exception:  # traced/abstract value
            return current_context()
        if dev.platform == "cpu":
            return Context("cpu", dev.id)
        return Context("tpu", dev.id)

    @property
    def context(self) -> Context:
        return self.ctx

    @property
    def T(self) -> "NDArray":
        return _invoke(jnp.transpose, self)

    @property
    def grad(self) -> Optional["NDArray"]:
        return self._grad

    @property
    def stype(self) -> str:
        return "default"

    # ------------------------------------------------------------- sync API
    def wait_to_read(self):
        d = self.data
        if hasattr(d, "block_until_ready"):
            d.block_until_ready()
        return self

    def asnumpy(self) -> _np.ndarray:
        return _np.asarray(self.data)

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def tolist(self):
        return self.asnumpy().tolist()

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    def __dlpack__(self, **kw):  # zero-copy interop
        return self.data.__dlpack__(**kw)

    # --------------------------------------------------------------- dunder
    def __len__(self) -> int:
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __bool__(self):
        if self.size != 1:
            raise MXNetError(
                "The truth value of an NDArray with multiple elements is ambiguous"
            )
        return bool(self.asscalar())

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __index__(self):
        return int(self.asscalar())

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        try:
            vals = _np.array2string(self.asnumpy(), precision=4, suppress_small=True)
        except Exception:  # traced / abstract
            vals = f"<abstract {self.data}>"
        shape = "x".join(str(s) for s in self.shape)
        return f"\n{vals}\n<NDArray {shape} @{self.ctx}>"

    # ------------------------------------------------------------- indexing
    def __getitem__(self, idx) -> "NDArray":
        idx = _unwrap_index(idx)
        if _recording_tracked(self):
            return _invoke(lambda d: d[idx], self)
        return NDArray(None, _view=_View(self, "slice", index=idx))

    def __setitem__(self, idx, value):
        _check_inplace_ok(self)
        idx = _unwrap_index(idx)
        value = _unwrap(value)
        if idx is Ellipsis or (isinstance(idx, slice) and idx == slice(None)):
            new = jnp.broadcast_to(
                jnp.asarray(value, dtype=self.data.dtype), self.shape
            )
            self._rebind(new)
            return
        self._rebind(self.data.at[idx].set(jnp.asarray(value)))

    def slice(self, begin, end, step=None) -> "NDArray":
        idx = tuple(
            slice(b, e, s)
            for b, e, s in zip(begin, end, step or [None] * len(begin))
        )
        return self[idx]

    def slice_axis(self, axis: int, begin: int, end: Optional[int]) -> "NDArray":
        idx = [slice(None)] * self.ndim
        idx[axis] = slice(begin, end)
        return self[tuple(idx)]

    def take(self, indices, axis=0, mode="clip") -> "NDArray":
        ind = _unwrap_index(indices)
        return _invoke(
            lambda d: jnp.take(d, ind, axis=axis, mode=mode), self
        )

    def pick(self, index, axis=-1, keepdims=False) -> "NDArray":
        ind = _unwrap_index(index)
        return _invoke(
            lambda d: jnp.take_along_axis(
                d, jnp.expand_dims(ind.astype(jnp.int32), axis), axis=axis
            ).squeeze(axis)
            if not keepdims
            else jnp.take_along_axis(
                d, jnp.expand_dims(ind.astype(jnp.int32), axis), axis=axis
            ),
            self,
        )

    # ------------------------------------------------------- shape changing
    def reshape(self, *shape, **kwargs) -> "NDArray":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = tuple(int(s) for s in shape)
        # mxnet convention: 0 keeps the input dim, -1 infers
        new = []
        for i, s in enumerate(shape):
            if s == 0 and not kwargs.get("reverse", False):
                new.append(self.shape[i])
            else:
                new.append(s)
        new = tuple(new)
        if _recording_tracked(self):
            return _invoke(lambda d: jnp.reshape(d, new), self)
        return NDArray(None, _view=_View(self, "reshape", shape=new))

    def reshape_like(self, other: "NDArray") -> "NDArray":
        return self.reshape(other.shape)

    def expand_dims(self, axis: int) -> "NDArray":
        return _invoke(lambda d: jnp.expand_dims(d, axis), self)

    def squeeze(self, axis=None) -> "NDArray":
        return _invoke(lambda d: jnp.squeeze(d, axis), self)

    def flatten(self) -> "NDArray":
        return self.reshape(self.shape[0], -1) if self.ndim > 1 else self.reshape(-1)

    def transpose(self, *axes) -> "NDArray":
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return _invoke(lambda d: jnp.transpose(d, axes or None), self)

    def swapaxes(self, a, b) -> "NDArray":
        return _invoke(lambda d: jnp.swapaxes(d, a, b), self)

    def broadcast_to(self, shape) -> "NDArray":
        return _invoke(lambda d: jnp.broadcast_to(d, tuple(shape)), self)

    def broadcast_like(self, other) -> "NDArray":
        return self.broadcast_to(other.shape)

    def tile(self, reps) -> "NDArray":
        return _invoke(lambda d: jnp.tile(d, reps), self)

    def repeat(self, repeats, axis=None) -> "NDArray":
        return _invoke(lambda d: jnp.repeat(d, repeats, axis=axis), self)

    # ------------------------------------------------------------ dtype/ctx
    def astype(self, dtype, copy=True) -> "NDArray":
        dt = jnp.dtype(dtype)
        return _invoke(lambda d: d.astype(dt), self)

    def copy(self) -> "NDArray":
        return NDArray(jnp.array(self.data))

    def copyto(self, other) -> "NDArray":
        if isinstance(other, NDArray):
            try:
                dev = next(iter(other.data.devices()))
                other._rebind(jax.device_put(self.data, dev))
            except Exception:
                other._rebind(self.data)
            return other
        if isinstance(other, Context):
            return NDArray(jax.device_put(self.data, other.jax_device()))
        raise TypeError(f"copyto does not support type {type(other)}")

    def as_in_context(self, ctx: Context) -> "NDArray":
        if ctx == self.ctx:
            return self
        return NDArray(jax.device_put(self.data, ctx.jax_device()))

    as_in_ctx = as_in_context

    def as_nd_ndarray(self):
        return self

    def as_np_ndarray(self):
        return self

    def tostype(self, stype: str) -> "NDArray":
        if stype != "default":
            raise MXNetError("sparse storage conversion: use mxnet_tpu.ndarray.sparse")
        return self

    def detach(self) -> "NDArray":
        return NDArray(self.data)

    # ------------------------------------------------------------- autograd
    def attach_grad(self, grad_req: str = "write", stype=None):
        from .. import autograd

        autograd._attach_grad(self, grad_req)

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd

        autograd.backward(
            [self],
            head_grads=[out_grad] if out_grad is not None else None,
            retain_graph=retain_graph,
            train_mode=train_mode,
        )

    def zero_grad(self):
        if self._grad is not None:
            self._grad._rebind(jnp.zeros_like(self._grad.data))

    # ------------------------------------------------------------ arithmetic
    def _binop(self, other, fn, reverse=False):
        a, b = (other, self) if reverse else (self, other)
        return _invoke(fn, a, b)

    def __add__(self, other):
        return self._binop(other, jnp.add)

    __radd__ = __add__

    def __sub__(self, other):
        return self._binop(other, jnp.subtract)

    def __rsub__(self, other):
        return self._binop(other, jnp.subtract, reverse=True)

    def __mul__(self, other):
        return self._binop(other, jnp.multiply)

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binop(other, jnp.divide)

    def __rtruediv__(self, other):
        return self._binop(other, jnp.divide, reverse=True)

    def __floordiv__(self, other):
        return self._binop(other, jnp.floor_divide)

    def __rfloordiv__(self, other):
        return self._binop(other, jnp.floor_divide, reverse=True)

    def __mod__(self, other):
        return self._binop(other, jnp.mod)

    def __rmod__(self, other):
        return self._binop(other, jnp.mod, reverse=True)

    def __pow__(self, other):
        return self._binop(other, jnp.power)

    def __rpow__(self, other):
        return self._binop(other, jnp.power, reverse=True)

    def __matmul__(self, other):
        return self._binop(other, jnp.matmul)

    def __rmatmul__(self, other):
        return self._binop(other, jnp.matmul, reverse=True)

    def __neg__(self):
        return _invoke(jnp.negative, self)

    def __abs__(self):
        return _invoke(jnp.abs, self)

    # in-place: rebind (reference mutated the buffer in place)
    def __iadd__(self, other):
        _check_inplace_ok(self)
        self._rebind(jnp.add(self.data, _unwrap(other)))
        return self

    def __isub__(self, other):
        _check_inplace_ok(self)
        self._rebind(jnp.subtract(self.data, _unwrap(other)))
        return self

    def __imul__(self, other):
        _check_inplace_ok(self)
        self._rebind(jnp.multiply(self.data, _unwrap(other)))
        return self

    def __itruediv__(self, other):
        _check_inplace_ok(self)
        self._rebind(jnp.divide(self.data, _unwrap(other)))
        return self

    # comparisons (not differentiated; mxnet returns same-dtype 0/1 arrays)
    def __eq__(self, other):
        if other is None:
            return NotImplemented
        return NDArray(jnp.equal(self.data, _unwrap(other)).astype(self.data.dtype))

    def __ne__(self, other):
        if other is None:
            return NotImplemented
        return NDArray(jnp.not_equal(self.data, _unwrap(other)).astype(self.data.dtype))

    def __lt__(self, other):
        return NDArray(jnp.less(self.data, _unwrap(other)).astype(self.data.dtype))

    def __le__(self, other):
        return NDArray(jnp.less_equal(self.data, _unwrap(other)).astype(self.data.dtype))

    def __gt__(self, other):
        return NDArray(jnp.greater(self.data, _unwrap(other)).astype(self.data.dtype))

    def __ge__(self, other):
        return NDArray(jnp.greater_equal(self.data, _unwrap(other)).astype(self.data.dtype))

    def __hash__(self):
        return id(self)

    # --------------------------------------------------------- reduce sugar
    def _reduce(self, fn, axis=None, keepdims=False):
        return _invoke(lambda d: fn(d, axis=axis, keepdims=keepdims), self)

    def sum(self, axis=None, keepdims=False, **kw):
        return self._reduce(jnp.sum, axis, keepdims)

    def mean(self, axis=None, keepdims=False, **kw):
        return self._reduce(jnp.mean, axis, keepdims)

    def max(self, axis=None, keepdims=False, **kw):
        return self._reduce(jnp.max, axis, keepdims)

    def min(self, axis=None, keepdims=False, **kw):
        return self._reduce(jnp.min, axis, keepdims)

    def prod(self, axis=None, keepdims=False, **kw):
        return self._reduce(jnp.prod, axis, keepdims)

    def std(self, axis=None, keepdims=False, **kw):
        return self._reduce(jnp.std, axis, keepdims)

    def var(self, axis=None, keepdims=False, **kw):
        return self._reduce(jnp.var, axis, keepdims)

    def norm(self, ord=None, axis=None, keepdims=False):
        return _invoke(
            lambda d: jnp.linalg.norm(d, ord=ord, axis=axis, keepdims=keepdims), self
        )

    def argmax(self, axis=None, **kw):
        return NDArray(jnp.argmax(self.data, axis=axis).astype(_DEFAULT_DTYPE))

    def argmin(self, axis=None, **kw):
        return NDArray(jnp.argmin(self.data, axis=axis).astype(_DEFAULT_DTYPE))

    def argsort(self, axis=-1, is_ascend=True):
        order = jnp.argsort(self.data, axis=axis)
        if not is_ascend:
            order = jnp.flip(order, axis=axis)
        return NDArray(order.astype(_DEFAULT_DTYPE))

    def clip(self, a_min=None, a_max=None):
        return _invoke(lambda d: jnp.clip(d, a_min, a_max), self)

    def abs(self):
        return _invoke(jnp.abs, self)

    def sqrt(self):
        return _invoke(jnp.sqrt, self)

    def square(self):
        return _invoke(jnp.square, self)

    def exp(self):
        return _invoke(jnp.exp, self)

    def log(self):
        return _invoke(jnp.log, self)

    def round(self):
        return _invoke(jnp.round, self)

    def floor(self):
        return _invoke(jnp.floor, self)

    def ceil(self):
        return _invoke(jnp.ceil, self)

    def sign(self):
        return _invoke(jnp.sign, self)

    def relu(self):
        return _invoke(lambda d: jnp.maximum(d, 0), self)

    def sigmoid(self):
        return _invoke(jax.nn.sigmoid, self)

    def tanh(self):
        return _invoke(jnp.tanh, self)

    def softmax(self, axis=-1):
        return _invoke(lambda d: jax.nn.softmax(d, axis=axis), self)

    def log_softmax(self, axis=-1):
        return _invoke(lambda d: jax.nn.log_softmax(d, axis=axis), self)

    def dot(self, other):
        return self._binop(other, jnp.dot)

    def one_hot(self, depth, on_value=1.0, off_value=0.0):
        return NDArray(
            jax.nn.one_hot(self.data.astype(jnp.int32), depth)
            * (on_value - off_value)
            + off_value
        )


# --------------------------------------------------------------- factories
def array(source, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    """Create an NDArray from any array-like (reference: ``mx.nd.array``)."""
    if isinstance(source, NDArray):
        data = source.data
    else:
        data = jnp.asarray(source)
    if dtype is not None:
        data = data.astype(jnp.dtype(dtype))
    elif data.dtype == jnp.float64:
        data = data.astype(_DEFAULT_DTYPE)
    return NDArray(data, ctx=ctx)


def empty(shape, ctx=None, dtype=None) -> NDArray:
    return NDArray(
        jnp.zeros(shape, dtype=jnp.dtype(dtype) if dtype else _DEFAULT_DTYPE), ctx=ctx
    )


def from_jax(data: jax.Array) -> NDArray:
    return NDArray(data)


def waitall():
    from ..engine import wait_for_all
    from ..imperative import flush_bulk

    flush_bulk()
    wait_for_all()
