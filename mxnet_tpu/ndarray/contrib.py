"""``mx.nd.contrib``: contrib operator namespace + control flow.

Reference: ``python/mxnet/ndarray/contrib.py`` [unverified] — generated
``_contrib_*`` op wrappers (exposed with the prefix stripped) plus the
hand-written control-flow helpers ``foreach`` / ``while_loop`` / ``cond``.
"""

from __future__ import annotations

import sys

from ..ops import registry as _registry
from . import register as _register

# control flow (hand-written, takes callables — cannot be registry ops)
from ..ops.control_flow import foreach, while_loop, cond  # noqa: F401

def _populate():
    mod = sys.modules[__name__]
    for name in _registry.list_ops():
        if not name.startswith("_contrib_"):
            continue
        op = _registry.get(name)
        fn = _register._make_op_func(op)
        setattr(mod, name, fn)
        setattr(mod, name[len("_contrib_"):], fn)
        for a in op.aliases:
            setattr(mod, a, fn)


_populate()
