"""``mx.nd``: the imperative NDArray namespace.

Reference: ``python/mxnet/ndarray/`` [unverified] — NDArray class plus op
functions generated from the registry at import time, with creation ops and
the ``random`` sub-namespace defined natively.
"""

from __future__ import annotations

import sys

import jax
import jax.numpy as jnp
import numpy as _np

from .ndarray import NDArray, array, empty, from_jax, waitall, _unwrap
from ..context import Context, current_context
from .. import ops as _ops  # ensure registry is populated
from . import register as _register
from . import random_ops as random  # mx.nd.random

_DEFAULT = jnp.float32


def _dt(dtype):
    return jnp.dtype(dtype) if dtype is not None else _DEFAULT


# ----------------------------------------------------------------- creation
def zeros(shape, ctx=None, dtype=None, **kw) -> NDArray:
    return NDArray(jnp.zeros(shape, _dt(dtype)), ctx=ctx)


def ones(shape, ctx=None, dtype=None, **kw) -> NDArray:
    return NDArray(jnp.ones(shape, _dt(dtype)), ctx=ctx)


def full(shape, val, ctx=None, dtype=None, **kw) -> NDArray:
    return NDArray(jnp.full(shape, val, _dt(dtype)), ctx=ctx)


def zeros_like(data, **kw) -> NDArray:
    return NDArray(jnp.zeros_like(_unwrap(data)))


def ones_like(data, **kw) -> NDArray:
    return NDArray(jnp.ones_like(_unwrap(data)))


def full_like(data, fill_value, **kw) -> NDArray:
    return NDArray(jnp.full_like(_unwrap(data), fill_value))


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None, **kw) -> NDArray:
    out = jnp.arange(start, stop, step, _dt(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return NDArray(out, ctx=ctx)


def linspace(start, stop, num, endpoint=True, ctx=None, dtype=None, **kw) -> NDArray:
    return NDArray(jnp.linspace(start, stop, num, endpoint=endpoint, dtype=_dt(dtype)), ctx=ctx)


# --------------------------------------------------------------- conversion
def save(fname: str, data):
    """Save NDArrays (reference: ``mx.nd.save`` binary format; here .npz)."""
    from ..util import save_ndarrays

    save_ndarrays(fname, data)


def load(fname: str):
    from ..util import load_ndarrays

    return load_ndarrays(fname)


def concatenate(arrays, axis=0, always_copy=True) -> NDArray:
    from ..imperative import invoke_fn

    return invoke_fn(lambda *xs: jnp.concatenate(xs, axis=axis), *arrays)


def add_n(*args, **kw) -> NDArray:
    from ..imperative import invoke_fn

    return invoke_fn(lambda *xs: sum(xs[1:], xs[0]), *args)


ElementWiseSum = add_n


def moveaxis(data, source, destination) -> NDArray:
    from ..imperative import invoke_fn

    return invoke_fn(lambda d: jnp.moveaxis(d, source, destination), data)


def batch_take(a, indices) -> NDArray:
    from ..imperative import invoke_fn

    return invoke_fn(
        lambda d, i: jnp.take_along_axis(d, i.astype(jnp.int32)[:, None], axis=1)[:, 0],
        a, indices,
    )


def true_divide(lhs, rhs):
    return lhs / rhs


def waitall_():  # legacy alias
    waitall()


# generated op functions (mx.nd.dot, mx.nd.Convolution, ...)
_register.populate_module(sys.modules[__name__], namespace="nd")
# the registry carries reference-named creation/like ops (_zeros,
# zeros_like, ...) for symbol-JSON loading; the NATIVE helpers above are
# the mx.nd surface (they keep ctx= device placement and the reference's
# val= spelling) — re-assert them over the generated namespace
for _native in (zeros, ones, full, zeros_like, ones_like, full_like,
                arange):
    setattr(sys.modules[__name__], _native.__name__, _native)

from . import sparse  # noqa: E402  (facade; row_sparse/csr)
from . import contrib  # noqa: E402  (mx.nd.contrib.* incl. control flow)
