"""Random sampling functions for ``mx.nd.random`` / ``mx.random``.

Reference: ``src/operator/random/`` samplers behind ``mx.nd.random.*``
[unverified]. Stateful API over splittable jax keys (see
``mxnet_tpu.random``); per-call key draws keep eager semantics while the
key-supply scope keeps hybridized graphs pure.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..context import Context
from ..random import next_key, next_threefry_key
from .ndarray import NDArray, _unwrap

__all__ = [
    "uniform", "normal", "randn", "randint", "gamma", "exponential",
    "poisson", "negative_binomial", "generalized_negative_binomial",
    "multinomial", "shuffle", "bernoulli",
]


def _wrap(data, ctx=None, dtype=None):
    if dtype is not None:
        data = data.astype(jnp.dtype(dtype))
    return NDArray(data, ctx=ctx if isinstance(ctx, Context) else None)


def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


def uniform(low=0.0, high=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    data = jax.random.uniform(
        next_key(), _shape(shape), minval=low, maxval=high, dtype=jnp.dtype(dtype)
    )
    if out is not None:
        out._rebind(data)
        return out
    return _wrap(data, ctx)


def normal(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None, out=None, **kw):
    data = loc + scale * jax.random.normal(next_key(), _shape(shape), dtype=jnp.dtype(dtype))
    if out is not None:
        out._rebind(data)
        return out
    return _wrap(data, ctx)


def randn(*shape, loc=0.0, scale=1.0, dtype="float32", ctx=None, **kw):
    return normal(loc, scale, shape, dtype=dtype, ctx=ctx)


def randint(low, high=None, shape=None, dtype="int32", ctx=None, out=None, **kw):
    if high is None:
        low, high = 0, low
    data = jax.random.randint(next_key(), _shape(shape), low, high, dtype=jnp.dtype(dtype))
    if out is not None:
        out._rebind(data)
        return out
    return _wrap(data, ctx)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", ctx=None, **kw):
    data = jax.random.gamma(next_key(), alpha, _shape(shape), dtype=jnp.dtype(dtype)) * beta
    return _wrap(data, ctx)


def exponential(scale=1.0, shape=None, dtype="float32", ctx=None, **kw):
    data = scale * jax.random.exponential(next_key(), _shape(shape), dtype=jnp.dtype(dtype))
    return _wrap(data, ctx)


def poisson(lam=1.0, shape=None, dtype="float32", ctx=None, **kw):
    data = jax.random.poisson(next_threefry_key(), lam,
                              _shape(shape)).astype(jnp.dtype(dtype))
    return _wrap(data, ctx)


def negative_binomial(k=1, p=1.0, shape=None, dtype="float32", ctx=None, **kw):
    g = jax.random.gamma(next_key(), k, _shape(shape)) * ((1 - p) / p)
    data = jax.random.poisson(next_threefry_key(), g).astype(jnp.dtype(dtype))
    return _wrap(data, ctx)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None, dtype="float32",
                                  ctx=None, **kw):
    r = 1.0 / alpha
    p = r / (r + mu)
    return negative_binomial(r, p, shape, dtype=dtype, ctx=ctx)


def multinomial(data, shape=None, get_prob=False, dtype="int32", **kw):
    probs = _unwrap(data)
    logits = jnp.log(jnp.maximum(probs, 1e-30))
    n = 1
    if shape:
        n = shape if isinstance(shape, int) else int(jnp.prod(jnp.asarray(shape)))
    out_shape = (probs.shape[0], n) if probs.ndim == 2 else (n,)
    samp = jax.random.categorical(next_key(), logits, axis=-1, shape=(
        (n, probs.shape[0]) if probs.ndim == 2 else (n,)
    ))
    if probs.ndim == 2:
        samp = samp.T
    if shape is None:
        samp = samp.squeeze(-1) if samp.ndim > probs.ndim - 1 else samp
    return NDArray(samp.astype(jnp.dtype(dtype)))


def bernoulli(prob=0.5, shape=None, dtype="float32", ctx=None, **kw):
    p = _unwrap(prob)
    data = jax.random.bernoulli(next_key(), p, _shape(shape) or None)
    return _wrap(data.astype(jnp.dtype(dtype)), ctx)


def shuffle(data, **kw):
    return NDArray(jax.random.permutation(next_key(), _unwrap(data), axis=0))
