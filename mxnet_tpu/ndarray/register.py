"""Build the ``mx.nd.*`` namespace from the op registry at import time.

TPU-native analogue of ``python/mxnet/ndarray/register.py`` [unverified]:
the reference listed the nnvm registry through the C ABI and code-generated
Python functions with docstrings; here we wrap each registered ``Operator``
in a dispatcher through ``imperative.invoke`` and install it on the target
module — same structural idea, one registry serving every frontend.
"""

from __future__ import annotations

import functools

from ..ops import registry as _registry


def _make_op_func(op: _registry.Operator):
    def op_func(*args, out=None, **kwargs):
        from ..imperative import invoke

        return invoke(op, *args, out=out, **kwargs)

    op_func.__name__ = op.name
    op_func.__qualname__ = op.name
    op_func.__doc__ = op.fn.__doc__ or f"Operator ``{op.name}``."
    return op_func


def populate_module(module, namespace: str = "nd"):
    """Install generated functions for all ops exposed in ``namespace``."""
    installed = []
    for name in _registry.list_ops():
        op = _registry.get(name)
        if namespace not in op.namespaces:
            continue
        fn = _make_op_func(op)
        setattr(module, name, fn)
        installed.append(name)
        for a in op.aliases:
            setattr(module, a, fn)
            installed.append(a)
    return installed
