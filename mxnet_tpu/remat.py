"""Activation rematerialization policies (reference: the NNVM sublinear
memory planner / memonger, after Chen et al., *Training Deep Nets with
Sublinear Memory Cost*).

Under XLA the plan collapses to ``jax.checkpoint`` over the traced
forward: the backward recomputes activations instead of loading saved
residuals — recompute FLOPs traded for HBM traffic, the standard lever
when the step is memory-bound. Two grains are wired through the stack:

- **whole-graph** — ``TrainStep(remat=policy)`` wraps the entire
  ``forward_loss`` (one checkpoint region; maximum memory saving,
  maximum recompute);
- **per-layer** — ``HybridBlock.hybridize(remat=policy)`` wraps each
  block that declares itself a remat unit (``_remat_unit = True``; the
  model-zoo transformer/BERT encoder+decoder layers do) in its own
  checkpoint region — the memonger segmentation, with the layer
  boundaries as the O(sqrt(N)) checkpoints.

Policies name what the forward may KEEP resident (everything else is
recomputed in backward):

- ``nothing_saveable`` (alias ``full``) — recompute everything.
- ``dots_saveable`` — keep matmul outputs (MXU results are the
  expensive recompute; the usual transformer policy).
- ``dots_with_no_batch_dims_saveable`` (alias ``dots``) — like
  ``dots_saveable`` but batched matmuls (attention scores) are also
  recomputed; keeps only weight-by-activation products.
- ``names:a,b,...`` — keep only values tagged
  ``mx.nd.checkpoint_name(x, 'a')`` (``jax.ad_checkpoint``'s
  names-based policy).

``MXTPU_REMAT`` sets the process default consumed by
``TrainStep(remat=None)``.
"""

from __future__ import annotations

import os

from .base import MXNetError

__all__ = ["POLICIES", "resolve_policy", "default_policy", "checkpoint"]

_OFF = ("", "0", "off", "false", "none")


def _named_policies():
    import jax

    p = jax.checkpoint_policies
    return {
        "full": None,
        "nothing_saveable": None,
        "dots": p.dots_with_no_batch_dims_saveable,
        "dots_with_no_batch_dims_saveable":
            p.dots_with_no_batch_dims_saveable,
        "dots_saveable": p.dots_saveable,
        "checkpoint_dots": p.dots_saveable,
    }


# public, for docs/tools; resolved lazily so importing this module never
# forces jax initialization
POLICIES = (
    "full", "nothing_saveable", "dots", "dots_with_no_batch_dims_saveable",
    "dots_saveable", "checkpoint_dots", "names:<n1,n2,...>",
)


def resolve_policy(name):
    """Policy spec -> ``jax.checkpoint`` policy callable (or None =
    ``nothing_saveable``). Accepts a policy name from ``POLICIES``, a
    ``names:a,b`` spec, an already-callable policy, or True (= 'full')."""
    if name is None:
        return None
    if callable(name):
        return name
    if name is True:
        return None  # legacy BERTEncoder(remat=True): recompute everything
    name = str(name).strip()
    named = _named_policies()
    if name in named:
        return named[name]
    if name.startswith("names:"):
        import jax

        tags = [t.strip() for t in name[len("names:"):].split(",") if t.strip()]
        if not tags:
            raise MXNetError("names-based remat policy needs at least one "
                             "tag: remat='names:attn_out,ffn_out'")
        return jax.checkpoint_policies.save_only_these_names(*tags)
    raise MXNetError(
        f"unknown remat policy {name!r}; choose one of {POLICIES}")


def default_policy():
    """Process-wide default (``MXTPU_REMAT``); None when unset/off."""
    v = os.environ.get("MXTPU_REMAT", "").strip().lower()
    if v in _OFF:
        return None
    resolve_policy(v)  # validate early: a typo'd env var fails loudly
    return v


def checkpoint(fn, policy=None):
    """``jax.checkpoint`` with a policy spec (name or callable)."""
    import jax

    return jax.checkpoint(fn, policy=resolve_policy(policy))
