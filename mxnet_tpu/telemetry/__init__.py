"""Unified training telemetry: step metrics, trace events, watchdog.

One spine for "why was this step slow?" and "is the run alive?":

- ``events``   — structured spans (Chrome-trace ``.json`` + append-only
  JSONL), thread-safe nesting, zero overhead when disabled.
- ``metrics``  — process-global registry (counters/gauges/rolling
  histograms): per-step wall time, samples/sec, JAX compile events
  (``jax.monitoring``), device memory, kvstore allreduce bytes/latency,
  and ``profiler.py``'s per-op aggregates (``op/`` family).
- ``watchdog`` — heartbeat file + stalled-step detection with thread
  stack dumps, nonzero exit on hard hangs.

Usage::

    import mxnet_tpu as mx
    mx.telemetry.enable()            # or MXNET_TELEMETRY=1 in the env
    ... train ...
    print(mx.telemetry.report())     # step-time p50/p95, samples/sec, ...
    mx.telemetry.dump()              # chrome://tracing-loadable trace.json

Env knobs: ``MXNET_TELEMETRY=1`` enables at import;
``MXNET_TELEMETRY_DIR`` sets the output directory (default
``./telemetry``); ``MXNET_TELEMETRY_WATCHDOG=1`` starts the watchdog on
enable; ``MXNET_TELEMETRY_HARD_TIMEOUT_S`` arms the hard-hang exit.

Hot paths gate on the module flag (``telemetry._ENABLED`` via
``enabled()``) so a disabled build pays a single flag check per step —
no span or metric objects are allocated.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from .events import EventLog, NULL_SPAN, now_us as _events_now_us
from .metrics import Counter, Gauge, Histogram, Registry, merge_summaries
from .watchdog import Watchdog

__all__ = [
    "enable", "disable", "enabled", "span", "instant", "complete",
    "clock_us", "registry", "report", "dump", "record_step",
    "start_watchdog", "stop_watchdog", "hbm_peak_bytes",
    "hbm_limit_bytes", "hbm_headroom_bytes", "device_memory_stats",
    "set_info", "run_info", "Registry", "Counter", "Gauge", "Histogram",
    "Watchdog", "EventLog", "NULL_SPAN", "merge_summaries",
]

# module-level fast flag: hot paths read `telemetry._ENABLED` directly —
# the whole disabled-mode cost is that one attribute load + branch
_ENABLED = False
_LOG: Optional[EventLog] = None
_REGISTRY = Registry()
_WATCHDOG: Optional[Watchdog] = None
_LOCK = threading.RLock()
_JAX_LISTENER_INSTALLED = False
# non-numeric run configuration surfaced in report() (amp dtype, remat
# policy, ...) — set by the components that own the knob, e.g. TrainStep
_RUN_INFO: dict = {}


def set_info(**kwargs):
    """Attach run-configuration facts (strings allowed — the registry is
    numeric-only) to ``report()``; None values clear the key."""
    for k, v in kwargs.items():
        if v is None:
            _RUN_INFO.pop(k, None)
        else:
            _RUN_INFO[k] = v


def run_info() -> dict:
    return dict(_RUN_INFO)


def enabled() -> bool:
    return _ENABLED


def registry() -> Registry:
    """The process-global metrics registry (usable even when event
    emission is disabled — metric objects are cheap and always live)."""
    return _REGISTRY


def default_dir() -> str:
    return os.environ.get("MXNET_TELEMETRY_DIR", "telemetry")


# ------------------------------------------------------------------ enable
def enable(directory: Optional[str] = None, watchdog: Optional[bool] = None,
           **watchdog_kwargs):
    """Turn on span emission (+ optionally the watchdog); idempotent.

    ``watchdog=None`` defers to ``MXNET_TELEMETRY_WATCHDOG``.
    """
    global _ENABLED, _LOG, _WATCHDOG
    with _LOCK:
        if _LOG is None:
            _LOG = EventLog(directory or default_dir())
        _ENABLED = True
        _install_jax_compile_listener()
        if watchdog is None:
            watchdog = os.environ.get(
                "MXNET_TELEMETRY_WATCHDOG", "0") not in ("0", "", "false")
        if watchdog and _WATCHDOG is None:
            start_watchdog(**watchdog_kwargs)
    return _LOG


def disable():
    """Stop emitting; buffered events stay dumpable via ``dump()``."""
    global _ENABLED, _WATCHDOG
    with _LOCK:
        _ENABLED = False
        if _WATCHDOG is not None:
            _WATCHDOG.stop()
            _WATCHDOG = None


def reset():
    """Full teardown (tests): drop the log, registry contents, watchdog."""
    global _ENABLED, _LOG
    with _LOCK:
        disable()
        if _LOG is not None:
            _LOG.close()
            _LOG = None
        _REGISTRY.clear()
        _RUN_INFO.clear()


# ------------------------------------------------------------------- spans
def span(name: str, args: Optional[dict] = None):
    """Context manager emitting one Chrome-trace span; a shared no-op
    singleton when disabled (no allocation)."""
    log = _LOG
    if not _ENABLED or log is None:
        return NULL_SPAN
    return log.span(name, args)


def instant(name: str, args: Optional[dict] = None):
    log = _LOG
    if _ENABLED and log is not None:
        log.instant(name, args)


def complete(name: str, ts_us: float, dur_us: float,
             args: Optional[dict] = None):
    """Emit one complete span with explicit start/duration (request-
    lifetime spans whose endpoints cross threads); no-op when disabled."""
    log = _LOG
    if _ENABLED and log is not None:
        log.complete(name, ts_us, dur_us, args)


def clock_us() -> float:
    """The process trace clock (µs since telemetry module import) — the
    timebase of every emitted event, exposed so the serving plane can
    answer clock-alignment probes (``ping``/``telemetry`` verbs)."""
    return _events_now_us()


# ------------------------------------------------------------------- steps
def record_step(samples: int, seconds: float):
    """Record one completed optimizer step: wall time + throughput
    accounting, and watchdog progress. Called by ``Trainer.step`` (only
    when telemetry is enabled) and available to custom loops."""
    _REGISTRY.histogram("trainer/step_time_s").observe(seconds)
    _REGISTRY.counter("trainer/steps").inc()
    _REGISTRY.counter("trainer/samples").inc(samples)
    wd = _WATCHDOG
    if wd is not None:
        wd.notify_step(seconds=seconds)
    _update_memory_gauges()


def _update_memory_gauges():
    peak = hbm_peak_bytes()
    if peak is not None:
        _REGISTRY.gauge("device/hbm_peak_bytes").max(peak)


# ---------------------------------------------------------------- watchdog
def start_watchdog(directory: Optional[str] = None, interval: float = 5.0,
                   stall_factor: float = 10.0, min_stall_s: float = 30.0,
                   hard_timeout_s: Optional[float] = None,
                   **kwargs) -> Watchdog:
    global _WATCHDOG
    with _LOCK:
        if _WATCHDOG is not None:
            return _WATCHDOG
        if hard_timeout_s is None:
            env = os.environ.get("MXNET_TELEMETRY_HARD_TIMEOUT_S")
            hard_timeout_s = float(env) if env else None
        _WATCHDOG = Watchdog(
            directory or (_LOG.directory if _LOG else default_dir()),
            interval=interval, stall_factor=stall_factor,
            min_stall_s=min_stall_s, hard_timeout_s=hard_timeout_s,
            **kwargs)
        _WATCHDOG.start()
        return _WATCHDOG


def stop_watchdog():
    global _WATCHDOG
    with _LOCK:
        if _WATCHDOG is not None:
            _WATCHDOG.stop()
            _WATCHDOG = None


def watchdog() -> Optional[Watchdog]:
    return _WATCHDOG


def _on_watchdog_stall(state: dict):
    """Watchdog -> telemetry bridge: count the stall and mark it in the
    trace so the gap is visible next to the last completed span."""
    _REGISTRY.counter("watchdog/stalls").inc()
    instant("watchdog.stall", {
        "step": state.get("step"),
        "idle_s": state.get("idle_s"),
        "stacks": state.get("stacks"),
    })


# ---------------------------------------------------------- device memory
def device_memory_stats():
    """Per-device ``memory_stats()`` dicts; empty list when the backend
    exposes none (CPU)."""
    try:
        import jax

        out = []
        for d in jax.local_devices():
            try:
                ms = d.memory_stats()
            except Exception:  # noqa: BLE001 - backend-dependent
                ms = None
            if ms:
                out.append({"device": str(d), **ms})
        return out
    except Exception:  # noqa: BLE001 - jax not importable in odd envs
        return []


def hbm_peak_bytes() -> Optional[int]:
    """Max peak-bytes-in-use over local devices; None on backends without
    memory stats (CPU) — null-safe by construction."""
    stats = device_memory_stats()
    peaks = [s.get("peak_bytes_in_use") for s in stats
             if s.get("peak_bytes_in_use") is not None]
    return max(peaks) if peaks else None


def hbm_limit_bytes() -> Optional[int]:
    """Per-device HBM capacity: min ``bytes_limit`` over local devices,
    falling back to ``MXTPU_HBM_BYTES`` (planning on rigs without memory
    stats, e.g. the CPU test backend). None when neither is known."""
    stats = device_memory_stats()
    limits = [s.get("bytes_limit") for s in stats
              if s.get("bytes_limit") is not None]
    if limits:
        return min(limits)
    env = os.environ.get("MXTPU_HBM_BYTES")
    if env:
        try:
            return int(float(env))
        except ValueError:
            pass
    return None


def hbm_headroom_bytes() -> Optional[int]:
    """HBM limit minus the high-water mark — how much larger the working
    set could grow. None when either side is unknown (CPU)."""
    limit = hbm_limit_bytes()
    peak = hbm_peak_bytes()
    if limit is None or peak is None:
        return None
    return limit - peak


# ------------------------------------------------------------ jax compile
def _install_jax_compile_listener():
    """Route ``jax.monitoring`` duration events (jit tracing/compilation)
    into the registry. Listener registration is append-only in jax, so
    the callback itself checks the ENABLED flag."""
    global _JAX_LISTENER_INSTALLED
    if _JAX_LISTENER_INSTALLED:
        return
    try:
        from jax import monitoring as _mon

        def _on_duration(event, duration, **kwargs):
            if not _ENABLED:
                return
            key = event.strip("/").replace("/", "_")
            _REGISTRY.histogram(f"jax/{key}").observe(duration)
            if "compil" in event or "backend_compile" in event:
                _REGISTRY.histogram("jax/compile_time_s").observe(duration)

        _mon.register_event_duration_secs_listener(_on_duration)
        _JAX_LISTENER_INSTALLED = True
    except Exception:  # noqa: BLE001 - jax without monitoring
        _JAX_LISTENER_INSTALLED = True  # don't retry every enable()


# ------------------------------------------------------------------ report
def _hp(snap, name, q):
    """Histogram percentile from a registry snapshot, None-safe."""
    h = snap["histograms"].get(name)
    return h[q] if h else None


def report() -> dict:
    """One-call run summary: step-time percentiles, throughput, compile
    time, HBM high-water mark, plus the full registry snapshot."""
    _update_memory_gauges()
    snap = _REGISTRY.snapshot()
    step_hist = snap["histograms"].get("trainer/step_time_s")
    compile_hist = snap["histograms"].get("jax/compile_time_s")
    wait_hist = snap["histograms"].get("input/wait_ms")
    samples = snap["counters"].get("trainer/samples", 0)
    step_sum = step_hist["sum"] if step_hist else 0.0
    return {
        "enabled": _ENABLED,
        "steps": snap["counters"].get("trainer/steps", 0),
        "step_time_p50": step_hist["p50"] if step_hist else None,
        "step_time_p95": step_hist["p95"] if step_hist else None,
        "step_time_p99": step_hist["p99"] if step_hist else None,
        "samples_per_sec": (samples / step_sum) if step_sum > 0 else None,
        "compile_time_s": compile_hist["sum"] if compile_hist else None,
        "hbm_peak_bytes": snap["gauges"].get("device/hbm_peak_bytes"),
        # memory/precision config + headroom (HBM-aware compute): the
        # dtype/remat knobs the run was built with and how much HBM is
        # left above the high-water mark (None on CPU)
        "amp_dtype": _RUN_INFO.get("amp_dtype"),
        "remat_policy": _RUN_INFO.get("remat_policy"),
        "hbm_headroom_bytes": hbm_headroom_bytes(),
        # SPMD sharding (parallel.sharding): the mesh/rules in force and
        # the shard/ family's headline figures — how many bytes one
        # device actually holds and the estimated per-step collective
        # traffic (None/absent in unsharded processes)
        "mesh_shape": _RUN_INFO.get("mesh_shape"),
        "sharding": _RUN_INFO.get("sharding"),
        "shard_param_bytes_total": snap["gauges"].get(
            "shard/param_bytes_total"),
        "shard_param_bytes_per_shard": snap["gauges"].get(
            "shard/param_bytes_per_shard"),
        "shard_collective_bytes_per_step": snap["gauges"].get(
            "shard/collective_bytes_per_step_est"),
        "watchdog_stalls": snap["counters"].get("watchdog/stalls", 0),
        # shape stability (compile_cache): distinct compiled signatures,
        # post-warmup recompiles (should stay 0), persistent-cache reuse
        "compile_signatures": snap["counters"].get("compile/signatures", 0),
        "compile_steady_state_recompiles": snap["counters"].get(
            "compile/steady_state_recompiles", 0),
        "compile_warmup_compiles": snap["counters"].get(
            "compile/warmup_compiles", 0),
        "compile_cache_hits": snap["counters"].get("compile/cache_hits", 0),
        "compile_cache_misses": snap["counters"].get(
            "compile/cache_misses", 0),
        # async device feed (gluon.data.prefetch): per-pull consumer stall
        # — after overlap, the residual input wait per step
        "input_wait_ms": wait_hist,
        "input_wait_ms_p50": wait_hist["p50"] if wait_hist else None,
        "input_wait_ms_p95": wait_hist["p95"] if wait_hist else None,
        "input_queue_depth": snap["gauges"].get("input/queue_depth"),
        # inference/serving (parallel.infer + serving.batcher): dispatch
        # prefill/decode timing, serving throughput, admission latency,
        # slot utilization — all None/0 in training-only processes
        "infer_prefill_ms_p50": _hp(snap, "infer/prefill_ms", "p50"),
        "infer_prefill_ms_p95": _hp(snap, "infer/prefill_ms", "p95"),
        "infer_decode_ms_per_token_p50": _hp(
            snap, "infer/decode_ms_per_token", "p50"),
        "infer_tokens_per_sec": snap["gauges"].get("infer/tokens_per_sec"),
        "infer_batch_occupancy": snap["gauges"].get(
            "infer/batch_occupancy"),
        "infer_queue_wait_ms_p50": _hp(snap, "infer/queue_wait_ms", "p50"),
        "infer_queue_wait_ms_p95": _hp(snap, "infer/queue_wait_ms", "p95"),
        "infer_requests": snap["counters"].get("infer/requests", 0),
        "infer_tokens": snap["counters"].get("infer/tokens", 0),
        # continuous batching + paged KV (serving.ContinuousBatcher /
        # serving.pages): time-to-first-token, pool pressure, per-
        # iteration admission and the backpressure/preemption self-
        # protection counters
        "infer_ttft_ms_p50": _hp(snap, "infer/ttft_ms", "p50"),
        "infer_ttft_ms_p95": _hp(snap, "infer/ttft_ms", "p95"),
        "infer_pages_in_use": snap["gauges"].get("infer/pages_in_use"),
        "infer_page_fragmentation": snap["gauges"].get(
            "infer/page_fragmentation"),
        "infer_admitted_per_iter_p50": _hp(
            snap, "infer/admitted_per_iter", "p50"),
        "infer_rejected_backpressure": snap["counters"].get(
            "infer/rejected_backpressure", 0),
        "infer_preempted": snap["counters"].get("infer/preempted", 0),
        # self-healing serving (serving.router/.watcher/.faults): which
        # weights are live and how often the plane healed itself — hot
        # swaps, replica evictions (failovers), transparent retries, and
        # the requests that were genuinely lost (should stay 0)
        "weights_version": _RUN_INFO.get("weights_version"),
        "serve_swaps": snap["counters"].get("serve/swaps", 0),
        "serve_swap_failures": snap["counters"].get(
            "serve/swap_failures", 0),
        "serve_failovers": snap["counters"].get("serve/failovers", 0),
        "serve_retries": snap["counters"].get("serve/retries", 0),
        "serve_dropped": snap["counters"].get("serve/dropped", 0),
        "serve_deadline_exceeded": snap["counters"].get(
            "serve/deadline_exceeded", 0),
        "serve_replica_restarts": snap["counters"].get(
            "serve/replica_restarts", 0),
        "serve_replicas_healthy": snap["gauges"].get(
            "serve/replicas_healthy"),
        "serve_faults_injected": snap["counters"].get(
            "serve/faults_injected", 0),
        "counters": snap["counters"],
        "gauges": snap["gauges"],
        "histograms": snap["histograms"],
    }


def dump(path: Optional[str] = None) -> Optional[str]:
    """Write the Chrome-trace JSON (plus a ``report.json`` snapshot next
    to it); returns the trace path, or None if never enabled."""
    log = _LOG
    if log is None:
        return None
    trace_path = log.dump(path)
    try:
        import json as _json

        with open(os.path.join(log.directory, "report.json"), "w") as f:
            _json.dump(report(), f, indent=2, default=str)
    except OSError:
        pass
    return trace_path


def jsonl_path() -> Optional[str]:
    return _LOG.jsonl_path if _LOG is not None else None


# auto-enable from the environment (MXNET_TELEMETRY=1 / true / yes)
if os.environ.get("MXNET_TELEMETRY", "0").lower() not in ("0", "", "false",
                                                          "no"):
    enable()
