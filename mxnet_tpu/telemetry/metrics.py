"""Metrics registry: counters, gauges, rolling histograms.

One process-global registry is the telemetry spine: the trainer records
step wall time and samples, the kvstore records allreduce bytes/latency,
``profiler.py``'s aggregate per-op stats live here too (``op/`` prefix),
and ``jax.monitoring`` compile events land under ``jax/``. The registry is
always usable (metric objects are a few machine words); the telemetry
ENABLED flag gates only hot-path instrumentation and event emission.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Optional

__all__ = ["Counter", "Gauge", "Histogram", "Registry",
           "merge_summaries"]


def merge_summaries(summaries) -> dict:
    """Merge per-replica ``Histogram.summary()`` dicts into one fleet
    summary (the scrape/aggregation plane, ``serving.tracing``).

    ``count``/``sum`` add exactly and ``min``/``max`` take extremes, so
    the fleet mean is exact. Percentiles cannot be recovered from
    summaries — the merged p50/p95/p99 are the count-weighted average of
    the inputs' percentiles, a documented approximation that is exact
    when the replicas' distributions agree and deterministic always
    (replaying a recorded scrape stream re-derives identical values).
    Empty inputs (count 0) are ignored; all-empty merges to the empty
    summary."""
    live = [s for s in summaries if s and s.get("count")]
    if not live:
        return {"count": 0, "sum": 0.0, "mean": None, "min": None,
                "max": None, "p50": None, "p95": None, "p99": None}
    count = sum(s["count"] for s in live)
    total = sum(s["sum"] for s in live)
    out = {
        "count": count,
        "sum": total,
        "mean": total / count,
        "min": min(s["min"] for s in live if s["min"] is not None),
        "max": max(s["max"] for s in live if s["max"] is not None),
    }
    for q in ("p50", "p95", "p99"):
        vals = [(s[q], s["count"]) for s in live if s[q] is not None]
        w = sum(c for _v, c in vals)
        out[q] = sum(v * c for v, c in vals) / w if w else None
    return out


class Counter:
    """Monotonic counter (allreduce bytes, samples, stall count)."""

    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value


class Gauge:
    """Last-write-wins value (HBM high-water mark, queue depth)."""

    __slots__ = ("_value",)

    def __init__(self):
        self._value = None

    def set(self, v):
        self._value = v

    def max(self, v):
        """Retain the high-water mark."""
        if self._value is None or v > self._value:
            self._value = v

    @property
    def value(self):
        return self._value


class Histogram:
    """Rolling-window histogram with cumulative count/sum.

    Percentiles come from the last ``window`` observations (a ring
    buffer — O(window) memory regardless of run length); ``count`` and
    ``sum`` are cumulative so rates (samples/sec over the whole run)
    stay exact.
    """

    __slots__ = ("_ring", "_idx", "_filled", "_count", "_sum", "_min",
                 "_max", "_lock", "window")

    def __init__(self, window: int = 1024):
        self.window = window
        self._ring = [0.0] * window
        self._idx = 0
        self._filled = 0
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._lock = threading.Lock()

    def observe(self, v: float):
        v = float(v)
        with self._lock:
            self._ring[self._idx] = v
            self._idx = (self._idx + 1) % self.window
            if self._filled < self.window:
                self._filled += 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def _window_sorted(self):
        with self._lock:
            vals = self._ring[: self._filled]
        return sorted(vals)

    def percentile(self, p: float) -> Optional[float]:
        """Nearest-rank-with-interpolation percentile of the rolling
        window; None when nothing was observed."""
        vals = self._window_sorted()
        if not vals:
            return None
        if len(vals) == 1:
            return vals[0]
        rank = (p / 100.0) * (len(vals) - 1)
        lo = int(math.floor(rank))
        hi = min(lo + 1, len(vals) - 1)
        frac = rank - lo
        return vals[lo] * (1 - frac) + vals[hi] * frac

    def summary(self) -> dict:
        vals = self._window_sorted()
        if not vals:
            return {"count": 0, "sum": 0.0, "mean": None, "min": None,
                    "max": None, "p50": None, "p95": None, "p99": None}
        return {
            "count": self._count,
            "sum": self._sum,
            "mean": self._sum / self._count,
            "min": self._min,
            "max": self._max,
            "p50": self.percentile(50),
            "p95": self.percentile(95),
            "p99": self.percentile(99),
        }


class Registry:
    """Get-or-create metric registry, thread-safe, name-keyed."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            m = self._counters.get(name)
            if m is None:
                m = self._counters[name] = Counter()
            return m

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            m = self._gauges.get(name)
            if m is None:
                m = self._gauges[name] = Gauge()
            return m

    def histogram(self, name: str, window: int = 1024) -> Histogram:
        with self._lock:
            m = self._histograms.get(name)
            if m is None:
                m = self._histograms[name] = Histogram(window)
            return m

    def clear(self, prefix: Optional[str] = None):
        """Drop metrics (all, or those whose name starts with prefix) —
        used by ``profiler.dumps(reset=True)`` for its ``op/`` family."""
        with self._lock:
            for d in (self._counters, self._gauges, self._histograms):
                if prefix is None:
                    d.clear()
                else:
                    for k in [k for k in d if k.startswith(prefix)]:
                        del d[k]

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        return {
            "counters": {k: v.value for k, v in counters.items()},
            "gauges": {k: v.value for k, v in gauges.items()},
            "histograms": {k: v.summary() for k, v in histograms.items()},
        }

    def histograms_with_prefix(self, prefix: str):
        with self._lock:
            return {k: v for k, v in self._histograms.items()
                    if k.startswith(prefix)}
