"""Structured trace events: Chrome-trace spans + append-only JSONL.

The reference instrumented every engine push and dumped Chrome-trace JSON
(``src/profiler/profiler.cc`` [unverified]); this module is that spine for
the TPU build's HOST side (the device timeline stays XProf's, see
``profiler.py``). Every span is one Chrome complete event (``ph: "X"``)
keyed by pid/tid, so nesting renders correctly in Perfetto/chrome://tracing
by ts/dur containment; a thread-local stack additionally stamps each record
with its ``depth`` and ``parent`` so the JSONL stream is self-describing
without a viewer.

Zero-overhead contract: when telemetry is disabled, ``span()`` returns a
shared no-op singleton — no per-call allocation — and hot paths that cannot
afford even that function call read the module flag directly
(``telemetry._ENABLED``).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

__all__ = ["EventLog", "span", "instant", "now_us"]

# monotonic origin for Chrome-trace timestamps (microseconds since process
# telemetry init; Chrome traces only need a consistent origin per file)
_T0 = time.perf_counter()


def _now_us() -> float:
    return (time.perf_counter() - _T0) * 1e6


def now_us() -> float:
    """This process's trace clock (µs since module import). Every event
    in this process's stream is stamped on this clock; cross-process
    alignment (tools/fleet_trace.py) estimates per-process offsets from
    it via the ``ping``/``telemetry`` verbs' ``clock_us`` reply field."""
    return _now_us()


class _NullSpan:
    """Shared disabled-mode span: one module-level instance, no state."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()

_TLS = threading.local()


def _stack():
    s = getattr(_TLS, "stack", None)
    if s is None:
        s = _TLS.stack = []
    return s


class _Span:
    __slots__ = ("_log", "name", "args", "_ts")

    def __init__(self, log, name, args):
        self._log = log
        self.name = name
        self.args = args

    def __enter__(self):
        _stack().append(self.name)
        self._ts = _now_us()
        return self

    def __exit__(self, *exc):
        ts_end = _now_us()
        stack = _stack()
        stack.pop()
        self._log.emit({
            "name": self.name,
            "ph": "X",
            "ts": self._ts,
            "dur": ts_end - self._ts,
            "pid": os.getpid(),
            "tid": threading.get_ident(),
            "depth": len(stack),
            "parent": stack[-1] if stack else None,
            "args": self.args or {},
        })
        return False


class EventLog:
    """Thread-safe event sink: bounded in-memory buffer (for the Chrome
    dump) + immediate append-only JSONL (crash-durable: the stream
    survives the hang the watchdog is there to catch)."""

    MAX_EVENTS = 200_000  # bound the buffer; drops are counted, not silent

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._events = []
        self._dropped = 0
        self._jsonl_path = os.path.join(directory, "events.jsonl")
        self._jsonl = open(self._jsonl_path, "a", buffering=1)

    @property
    def jsonl_path(self) -> str:
        return self._jsonl_path

    # ------------------------------------------------------------- emit
    def emit(self, event: dict):
        try:
            line = json.dumps(event)
        except TypeError:
            # non-serializable args: keep the span, stringify the payload
            event = dict(event, args={"repr": repr(event.get("args"))})
            line = json.dumps(event)
        with self._lock:
            if len(self._events) < self.MAX_EVENTS:
                self._events.append(event)
            else:
                self._dropped += 1
            try:
                self._jsonl.write(line + "\n")
            except ValueError:  # closed file during interpreter teardown
                pass

    def span(self, name: str, args: Optional[dict] = None) -> _Span:
        return _Span(self, name, args)

    def complete(self, name: str, ts_us: float, dur_us: float,
                 args: Optional[dict] = None):
        """Emit one complete span (``ph: "X"``) with explicit start/dur —
        for request-lifetime spans whose endpoints live on different
        threads (router submit→resolve, batcher enqueue→retire), where a
        ``with``-block cannot bracket the interval. Bypasses the
        thread-local nesting stack: depth/parent only make sense for
        lexically nested spans."""
        self.emit({
            "name": name,
            "ph": "X",
            "ts": ts_us,
            "dur": max(dur_us, 0.0),
            "pid": os.getpid(),
            "tid": threading.get_ident(),
            "args": args or {},
        })

    def instant(self, name: str, args: Optional[dict] = None):
        """Instant event (``ph: "i"``) — phase markers like checkpoint
        commits and watchdog stall flags."""
        self.emit({
            "name": name,
            "ph": "i",
            "ts": _now_us(),
            "s": "p",
            "pid": os.getpid(),
            "tid": threading.get_ident(),
            "args": args or {},
        })

    # ------------------------------------------------------------- dump
    def chrome_events(self) -> list:
        with self._lock:
            events = list(self._events)
        out = [{
            "name": "process_name",
            "ph": "M",
            "pid": os.getpid(),
            "args": {"name": "mxnet_tpu host telemetry"},
        }]
        for e in events:
            ce = {k: e[k] for k in ("name", "ph", "ts", "pid", "tid")
                  if k in e}
            if "dur" in e:
                ce["dur"] = e["dur"]
            if "s" in e:
                ce["s"] = e["s"]
            args = dict(e.get("args") or {})
            if e.get("parent"):
                args["parent"] = e["parent"]
            ce["args"] = args
            out.append(ce)
        return out

    def dump(self, path: Optional[str] = None) -> str:
        """Write the buffered spans as a Chrome-trace JSON file."""
        path = path or os.path.join(self.directory, "trace.json")
        with open(path, "w") as f:
            json.dump({
                "traceEvents": self.chrome_events(),
                "displayTimeUnit": "ms",
                "otherData": {"dropped_events": self._dropped},
            }, f)
        return path

    def close(self):
        with self._lock:
            try:
                self._jsonl.close()
            except Exception:  # noqa: BLE001 - teardown
                pass


def span(log: Optional[EventLog], name: str, args: Optional[dict] = None):
    return log.span(name, args) if log is not None else NULL_SPAN


def instant(log: Optional[EventLog], name: str,
            args: Optional[dict] = None):
    if log is not None:
        log.instant(name, args)
