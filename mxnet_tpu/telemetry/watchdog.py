"""Heartbeat file + hang/slow-step watchdog.

Motivation (round-5 bench): a tunnel outage hung the bench for 540 s and
the process still exited 0 with value 0.0 — a dead run was
indistinguishable from a clean one. This module makes liveness a
first-class artifact:

- a background thread writes ``heartbeat.json`` (last completed step +
  wall/monotonic timestamps) every ``interval`` seconds, so an external
  supervisor can distinguish "alive and stepping" from "wedged" without
  attaching anything to the process;
- a STALL fires when no step completes for ``stall_factor`` x the
  rolling-MEDIAN step time (floored at ``min_stall_s``): the watchdog
  dumps every thread's stack via ``faulthandler`` (signal handlers cannot
  preempt a main thread blocked inside the tunnel's C RPC, but
  faulthandler runs from THIS thread and inspects the others) and emits a
  telemetry instant event;
- a HARD HANG (no progress for ``hard_timeout_s``) dumps stacks one last
  time, flushes the heartbeat with ``status: "hard_hang"`` and
  ``os._exit``\\ s nonzero so the process status finally agrees with
  reality.
"""

from __future__ import annotations

import collections
import faulthandler
import json
import os
import statistics
import threading
import time
from typing import Callable, Optional

__all__ = ["Watchdog", "read_heartbeat"]


def read_heartbeat(path: str) -> Optional[dict]:
    """Parse a ``heartbeat.json``; None when missing or torn.

    The writer publishes via ``os.replace`` so a torn read should be
    impossible on a POSIX filesystem — but a health check must never
    crash on a weird one, so decode failures degrade to None (= unknown)
    rather than raising."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


class Watchdog:
    """Progress monitor for step-structured work.

    ``notify_step(seconds)`` is the only hot-path call (lock + deque
    append). Everything else happens on the watchdog thread.
    """

    def __init__(self, directory: str, interval: float = 5.0,
                 stall_factor: float = 10.0, min_stall_s: float = 30.0,
                 hard_timeout_s: Optional[float] = None,
                 exit_code: int = 43,
                 on_stall: Optional[Callable[[dict], None]] = None,
                 _exit_fn: Optional[Callable[[int], None]] = None):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.heartbeat_path = os.path.join(directory, "heartbeat.json")
        self.interval = float(interval)
        self.stall_factor = float(stall_factor)
        self.min_stall_s = float(min_stall_s)
        self.hard_timeout_s = hard_timeout_s
        self.exit_code = int(exit_code)
        self.on_stall = on_stall
        self._exit_fn = _exit_fn or os._exit
        self._lock = threading.Lock()
        self._step = 0
        self._last_progress = time.monotonic()
        self._step_times = collections.deque(maxlen=64)
        self._inflight = 0
        self._last_request_id = None
        self._requests_completed = 0
        self._stalled = False
        self.stall_count = 0
        self._stop = threading.Event()
        self._thread = None

    # ------------------------------------------------------------ control
    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._last_progress = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, name="mxtpu-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.interval + 1.0)
        self._write_heartbeat(status="stopped")

    # ----------------------------------------------------------- hot path
    def notify_step(self, seconds: Optional[float] = None,
                    step: Optional[int] = None):
        with self._lock:
            self._step = self._step + 1 if step is None else int(step)
            self._last_progress = time.monotonic()
            if seconds is not None:
                self._step_times.append(float(seconds))
            self._stalled = False

    def note_request(self, inflight=None, request_id=None, completed=0):
        """Request-level progress for the heartbeat (serving batchers):
        lets a health reader distinguish "hung with work" from "idle"
        straight from ``heartbeat.json``, without an RPC scrape. Same
        hot-path contract as ``notify_step`` — one lock, a few stores."""
        with self._lock:
            if inflight is not None:
                self._inflight = int(inflight)
            if request_id is not None:
                self._last_request_id = request_id
            if completed:
                self._requests_completed += int(completed)

    # ------------------------------------------------------------- thread
    def _stall_threshold(self) -> Optional[float]:
        """None until a step time exists — a run that never stepped is a
        startup/compile phase, not a stall (the hard timeout still
        covers it)."""
        with self._lock:
            if not self._step_times:
                return None
            med = statistics.median(self._step_times)
        return max(self.min_stall_s, self.stall_factor * med)

    def _state(self) -> dict:
        with self._lock:
            idle = time.monotonic() - self._last_progress
            return {
                "step": self._step,
                "idle_s": idle,
                "median_step_s": (statistics.median(self._step_times)
                                  if self._step_times else None),
                "inflight": self._inflight,
                "last_request_id": self._last_request_id,
                "requests_completed": self._requests_completed,
            }

    def _write_heartbeat(self, status="alive"):
        if self._heartbeat_suppressed():
            return
        state = self._state()
        state.update({
            "status": status,
            "pid": os.getpid(),
            "time": time.time(),
            "monotonic": time.monotonic(),
        })
        # atomic publish: unique tmp per writer (two watchdogs sharing a
        # directory never interleave into one tmp file), fsync'd before
        # the rename so the visible file is always complete JSON — a
        # router health-reading this file concurrently can never observe
        # a partial write
        tmp = (f"{self.heartbeat_path}.{os.getpid()}"
               f".{threading.get_ident()}.tmp")
        try:
            with open(tmp, "w") as f:
                json.dump(state, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.heartbeat_path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _heartbeat_suppressed(self) -> bool:
        """Fault point ``watchdog.heartbeat`` (serving.faults): a stale
        heartbeat with the process otherwise alive — the condition the
        serving router's health scoring must catch."""
        try:
            from ..serving import faults as _faults
        except Exception:  # noqa: BLE001 - minimal installs
            return False
        return _faults.check("watchdog.heartbeat",
                             tag=self.directory) is not None

    def _dump_stacks(self, tag: str) -> Optional[str]:
        path = os.path.join(self.directory, f"stacks_{tag}.txt")
        try:
            with open(path, "w") as f:
                f.write(f"# {tag} at {time.strftime('%Y-%m-%dT%H:%M:%S')} "
                        f"pid={os.getpid()}\n")
                faulthandler.dump_traceback(file=f, all_threads=True)
            return path
        except OSError:
            return None

    def _fire_stall(self):
        state = self._state()
        state["stacks"] = self._dump_stacks(f"stall_step{state['step']}")
        self.stall_count += 1
        self._write_heartbeat(status="stalled")
        try:
            from . import _on_watchdog_stall

            _on_watchdog_stall(state)
        except Exception:  # noqa: BLE001 - telemetry must not kill the run
            pass
        if self.on_stall is not None:
            try:
                self.on_stall(state)
            except Exception:  # noqa: BLE001 - user callback
                pass

    def _run(self):
        while not self._stop.wait(self.interval):
            self._write_heartbeat()
            with self._lock:
                idle = time.monotonic() - self._last_progress
                stalled = self._stalled
            if self.hard_timeout_s is not None and \
                    idle > self.hard_timeout_s:
                self._dump_stacks("hard_hang")
                self._write_heartbeat(status="hard_hang")
                self._exit_fn(self.exit_code)
                return  # only reached with an injected _exit_fn (tests)
            threshold = self._stall_threshold()
            if threshold is not None and idle > threshold and not stalled:
                with self._lock:
                    self._stalled = True
                self._fire_stall()
