"""RNG state: stateful seeding API over functional jax keys.

The reference kept per-device parallel RNG states handed to ops as engine
resources (``src/common/random_generator.h``, ``ResourceRequest::kRandom``
[unverified]) behind a stateful ``mx.random.seed()`` API. Here the same API
fronts a splittable jax PRNG key:

- Eager ops draw keys by splitting a module-global key (stateful, like the
  reference).
- Under ``hybridize()``/jit tracing, drawing from global state would bake a
  constant into the compiled program, so a *key supply* scope provides a
  traced key that stochastic ops split deterministically; the CachedOp passes
  a fresh key argument per call, keeping dropout random across steps while
  the compiled program stays pure.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["seed", "next_key", "key_supply", "KeySupply", "current_key_supply"]

_LOCK = threading.Lock()
# host-side (seed, counter) state: next_key derives key = fold_in(PRNGKey(seed),
# counter). Never stores a computed key array back — a computed key could be a
# tracer when drawn inside a jit/eval_shape trace and would leak out.
_GLOBAL_SEED = 0
_GLOBAL_COUNTER = 0
_SUPPLY = threading.local()


def seed(seed_state: int, ctx=None):
    """Reference: ``mx.random.seed``; ctx accepted for compatibility."""
    global _GLOBAL_SEED, _GLOBAL_COUNTER
    with _LOCK:
        _GLOBAL_SEED = int(seed_state)
        _GLOBAL_COUNTER = 0


class KeySupply:
    """Deterministic key splitter for one traced invocation."""

    def __init__(self, key):
        self._key = key

    def next(self):
        self._key, sub = jax.random.split(self._key)
        return sub


def current_key_supply() -> Optional[KeySupply]:
    stack = getattr(_SUPPLY, "stack", None)
    return stack[-1] if stack else None


class key_supply:
    """Context manager installing a KeySupply for jit-traced stochastic ops."""

    def __init__(self, key):
        self._supply = KeySupply(key)

    def __enter__(self):
        if not hasattr(_SUPPLY, "stack"):
            _SUPPLY.stack = []
        _SUPPLY.stack.append(self._supply)
        return self._supply

    def __exit__(self, *exc):
        _SUPPLY.stack.pop()
        return False


def next_key():
    """Draw a fresh PRNG key (supply-scoped if tracing, else global state)."""
    supply = current_key_supply()
    if supply is not None:
        return supply.next()
    global _GLOBAL_COUNTER
    with _LOCK:
        _GLOBAL_COUNTER += 1
        count = _GLOBAL_COUNTER
    return jax.random.fold_in(jax.random.PRNGKey(_GLOBAL_SEED), count)


def next_threefry_key():
    """A fresh key in the threefry impl, whatever the session PRNG is.

    jax.random.poisson supports only threefry; under the default rbg
    PRNG (MXNET_TPU_PRNG) every poisson-based sampler derives its key
    here — deterministic given the global state."""
    k = next_key()
    data = jax.random.key_data(k).reshape(-1)[:2].astype(jnp.uint32)
    return jax.random.wrap_key_data(data, impl="threefry2x32")
