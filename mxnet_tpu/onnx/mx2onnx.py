"""mx -> ONNX export (reference: ``python/mxnet/onnx/mx2onnx`` op-by-op
converters [unverified]).

Walks the Symbol DAG topologically, emitting one (or a few) ONNX nodes
per operator into a wire-compatible ModelProto built on the vendored
schema subset (``onnx_subset.proto`` — standard field numbers, so any
ONNX runtime parses the output). Parameters become initializers; free
variables become graph inputs. Opset 17 (LayerNormalization needs 17;
everything else is 13-stable).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as _np

from ..base import MXNetError
from . import onnx_subset_pb2 as P

OPSET = 17

_DTYPE = {
    _np.dtype("float32"): P.TensorProto.FLOAT,
    _np.dtype("float64"): P.TensorProto.DOUBLE,
    _np.dtype("float16"): P.TensorProto.FLOAT16,
    _np.dtype("int32"): P.TensorProto.INT32,
    _np.dtype("int64"): P.TensorProto.INT64,
    _np.dtype("int8"): P.TensorProto.INT8,
    _np.dtype("uint8"): P.TensorProto.UINT8,
    _np.dtype("bool"): P.TensorProto.BOOL,
}


def _tensor(name: str, arr: _np.ndarray) -> P.TensorProto:
    t = P.TensorProto()
    t.name = name
    t.dims.extend(arr.shape)
    dt = _DTYPE.get(arr.dtype)
    if dt is None:
        raise MXNetError(f"ONNX export: unsupported dtype {arr.dtype}")
    t.data_type = dt
    t.raw_data = _np.ascontiguousarray(arr).tobytes()
    return t


def _np_to_elem(dtype) -> int:
    dt = _DTYPE.get(_np.dtype(dtype))
    if dt is None:
        raise MXNetError(f"ONNX export: unsupported input dtype {dtype}")
    return dt


def _vinfo(name: str, shape=None, elem=P.TensorProto.FLOAT):
    v = P.ValueInfoProto()
    v.name = name
    v.type.tensor_type.elem_type = elem
    if shape is not None:
        for d in shape:
            dim = v.type.tensor_type.shape.dim.add()
            dim.dim_value = int(d)
    # shape=None leaves the shape field unset (unknown rank); an empty
    # TensorShapeProto would declare a rank-0 scalar per ONNX semantics.
    return v


class _Builder:
    """Accumulates nodes/initializers; hands converters fresh names."""

    def __init__(self):
        self.nodes: List[P.NodeProto] = []
        self.initializers: List[P.TensorProto] = []
        self.params: Dict[str, tuple] = {}  # bound param name -> shape
        self.replaced: set = set()  # params a converter substituted
        self._n = 0

    def node(self, op_type: str, inputs, outputs, name=None, **attrs):
        n = P.NodeProto()
        n.op_type = op_type
        n.name = name or f"{op_type.lower()}_{len(self.nodes)}"
        n.input.extend(inputs)
        n.output.extend(outputs)
        for k, v in attrs.items():
            if v is None:
                continue
            a = n.attribute.add()
            a.name = k
            if isinstance(v, bool):
                a.type = P.AttributeProto.INT
                a.i = int(v)
            elif isinstance(v, int):
                a.type = P.AttributeProto.INT
                a.i = v
            elif isinstance(v, float):
                a.type = P.AttributeProto.FLOAT
                a.f = v
            elif isinstance(v, str):
                a.type = P.AttributeProto.STRING
                a.s = v.encode()
            elif isinstance(v, (list, tuple)):
                if all(isinstance(x, int) for x in v):
                    a.type = P.AttributeProto.INTS
                    a.ints.extend(v)
                else:
                    a.type = P.AttributeProto.FLOATS
                    a.floats.extend(float(x) for x in v)
            else:
                raise MXNetError(f"ONNX export: bad attr {k}={v!r}")
        self.nodes.append(n)
        return n

    def const(self, arr: _np.ndarray, hint="const") -> str:
        name = f"_{hint}_{self._n}"
        self._n += 1
        self.initializers.append(_tensor(name, _np.asarray(arr)))
        return name


# converter registry: mx op name -> fn(b, name, ins, attrs, out) where
# `ins` are the ONNX input value names and `out` the output value name
_CONVERTERS: Dict[str, Callable] = {}


def _conv(name):
    def deco(fn):
        for n in ([name] if isinstance(name, str) else name):
            _CONVERTERS[n] = fn
        return fn

    return deco


def _shape_attr(attrs, key, nd=2, default=None):
    v = attrs.get(key, default)
    if v is None:
        return None
    if isinstance(v, int):
        return (v,) * nd
    return tuple(int(x) for x in v)


@_conv("Convolution")
def _c_conv(b, name, ins, attrs, out):
    kernel = _shape_attr(attrs, "kernel")
    nd = len(kernel)
    stride = _shape_attr(attrs, "stride", nd, 1)
    dilate = _shape_attr(attrs, "dilate", nd, 1)
    pad = _shape_attr(attrs, "pad", nd, 0)
    b.node("Conv", ins, [out], name=name, kernel_shape=list(kernel),
           strides=list(stride), dilations=list(dilate),
           pads=list(pad) + list(pad), group=int(attrs.get("num_group", 1)))


@_conv("FullyConnected")
def _c_fc(b, name, ins, attrs, out):
    x, w = ins[0], ins[1]
    bias = ins[2] if len(ins) > 2 else None
    if attrs.get("flatten", True):
        flat = f"{name}_flat"
        b.node("Flatten", [x], [flat], axis=1)
        gemm_in = [flat, w] + ([bias] if bias else [])
        if bias:
            b.node("Gemm", gemm_in, [out], name=name, transB=1)
        else:
            b.node("Gemm", gemm_in, [out], name=name, transB=1, beta=0.0)
    else:
        wt = f"{name}_wT"
        b.node("Transpose", [w], [wt], perm=[1, 0])
        mm = f"{name}_mm" if bias else out
        b.node("MatMul", [x, wt], [mm], name=name)
        if bias:
            b.node("Add", [mm, bias], [out])


@_conv("BatchNorm")
def _c_bn(b, name, ins, attrs, out):
    if int(attrs.get("axis", 1)) != 1:
        raise MXNetError(
            "ONNX export: BatchNorm axis != 1 (ONNX BatchNormalization "
            "is channel-axis-1 only); transpose around the layer instead")
    gamma = ins[1]
    if attrs.get("fix_gamma", True):
        # mx semantics: gamma frozen at 1 regardless of the stored param
        # (reference default) — emit a ones initializer of the param's
        # shape in its place, and drop the now-dead stored gamma so it
        # cannot resurface as a stale arg_param on re-import
        shape = b.params.get(ins[1])
        if shape is None:
            raise MXNetError(
                f"ONNX export: BatchNorm {name} has fix_gamma=True but "
                f"gamma {ins[1]!r} is not a bound parameter; pass it in "
                "params or set fix_gamma=False")
        gamma = b.const(_np.ones(shape, _np.float32), "fixed_gamma")
        b.replaced.add(ins[1])
    b.node("BatchNormalization",
           [ins[0], gamma, ins[2], ins[3], ins[4]], [out], name=name,
           epsilon=float(attrs.get("eps", 1e-3)),
           momentum=float(attrs.get("momentum", 0.9)))


_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softrelu": "Softplus", "softsign": "Softsign"}


@_conv("Activation")
def _c_act(b, name, ins, attrs, out):
    t = attrs.get("act_type", "relu")
    if t not in _ACT:
        raise MXNetError(f"ONNX export: Activation act_type {t!r}")
    b.node(_ACT[t], ins[:1], [out], name=name)


@_conv("LeakyReLU")
def _c_leaky(b, name, ins, attrs, out):
    if attrs.get("act_type", "leaky") not in ("leaky", "prelu"):
        raise MXNetError("ONNX export: only leaky/prelu LeakyReLU")
    if attrs.get("act_type", "leaky") == "prelu":
        b.node("PRelu", ins[:2], [out], name=name)
    else:
        b.node("LeakyRelu", ins[:1], [out], name=name,
               alpha=float(attrs.get("slope", 0.25)))


@_conv("Pooling")
def _c_pool(b, name, ins, attrs, out):
    ptype = attrs.get("pool_type", "max")
    if attrs.get("global_pool", False):
        b.node({"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}[ptype],
               ins[:1], [out], name=name)
        return
    kernel = _shape_attr(attrs, "kernel")
    nd = len(kernel)
    stride = _shape_attr(attrs, "stride", nd, 1)
    pad = _shape_attr(attrs, "pad", nd, 0)
    common = dict(kernel_shape=list(kernel), strides=list(stride),
                  pads=list(pad) + list(pad))
    if attrs.get("pooling_convention", "valid") == "full":
        common["ceil_mode"] = 1
    if ptype == "max":
        b.node("MaxPool", ins[:1], [out], name=name, **common)
    elif ptype == "avg":
        b.node("AveragePool", ins[:1], [out], name=name,
               count_include_pad=int(attrs.get("count_include_pad", True)),
               **common)
    else:
        raise MXNetError(f"ONNX export: pool_type {ptype!r}")


@_conv("Flatten")
def _c_flatten(b, name, ins, attrs, out):
    b.node("Flatten", ins[:1], [out], name=name, axis=1)


@_conv("Reshape")
def _c_reshape(b, name, ins, attrs, out):
    shape = attrs.get("shape")
    if shape is None:
        raise MXNetError("ONNX export: Reshape needs a shape attr")
    s = b.const(_np.asarray(shape, _np.int64), "shape")
    b.node("Reshape", [ins[0], s], [out], name=name)


@_conv("concat")
def _c_concat(b, name, ins, attrs, out):
    b.node("Concat", ins, [out], name=name, axis=int(attrs.get("dim", 1)))


_BINOP = {"broadcast_add": "Add", "broadcast_sub": "Sub",
          "broadcast_mul": "Mul", "broadcast_div": "Div",
          "broadcast_maximum": "Max", "broadcast_minimum": "Min",
          "broadcast_power": "Pow", "dot": "MatMul", "batch_dot": "MatMul"}


for _mx, _ox in _BINOP.items():
    def _mk(ox):
        def f(b, name, ins, attrs, out):
            b.node(ox, ins[:2], [out], name=name)

        return f

    _CONVERTERS[_mx] = _mk(_ox)

_UNOP = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
         "exp": "Exp", "log": "Log", "sqrt": "Sqrt", "abs": "Abs",
         "negative": "Neg", "floor": "Floor", "ceil": "Ceil",
         "erf": "Erf", "sign": "Sign", "identity": "Identity",
         "BlockGrad": "Identity", "reciprocal": "Reciprocal",
         "sin": "Sin", "cos": "Cos"}

for _mx, _ox in _UNOP.items():
    def _mk1(ox):
        def f(b, name, ins, attrs, out):
            b.node(ox, ins[:1], [out], name=name)

        return f

    _CONVERTERS[_mx] = _mk1(_ox)


@_conv(["softmax", "SoftmaxActivation", "SoftmaxOutput"])
def _c_softmax(b, name, ins, attrs, out):
    b.node("Softmax", ins[:1], [out], name=name,
           axis=int(attrs.get("axis", -1)))


@_conv("log_softmax")
def _c_log_softmax(b, name, ins, attrs, out):
    b.node("LogSoftmax", ins[:1], [out], name=name,
           axis=int(attrs.get("axis", -1)))


@_conv("Dropout")
def _c_dropout(b, name, ins, attrs, out):
    # inference-mode export: identity (the reference exporter emitted
    # Dropout with ratio; runtimes ignore it at inference — Identity is
    # the same result without relying on that)
    b.node("Identity", ins[:1], [out], name=name)


@_conv("transpose")
def _c_transpose(b, name, ins, attrs, out):
    axes = attrs.get("axes")
    if axes:
        b.node("Transpose", ins[:1], [out], name=name,
               perm=[int(a) for a in axes])
    else:
        b.node("Transpose", ins[:1], [out], name=name)


@_conv("add_n")
def _c_add_n(b, name, ins, attrs, out):
    b.node("Sum", ins, [out], name=name)


@_conv("clip")
def _c_clip(b, name, ins, attrs, out):
    # missing bounds are UNBOUNDED (mx a_min/a_max=None) — emit the ONNX
    # optional-input placeholder, never a spurious 0.0
    a_min = attrs.get("a_min")
    a_max = attrs.get("a_max")
    inputs = [ins[0]]
    inputs.append(b.const(_np.float32(a_min), "min")
                  if a_min is not None else "")
    if a_max is not None:
        inputs.append(b.const(_np.float32(a_max), "max"))
    while inputs and inputs[-1] == "":
        inputs.pop()
    b.node("Clip", inputs, [out], name=name)


@_conv("slice_axis")
def _c_slice_axis(b, name, ins, attrs, out):
    axis = int(attrs["axis"])
    begin = int(attrs.get("begin", 0))
    end = attrs.get("end")
    end = _np.iinfo(_np.int64).max if end is None else int(end)
    b.node("Slice", [
        ins[0],
        b.const(_np.asarray([begin], _np.int64), "starts"),
        b.const(_np.asarray([end], _np.int64), "ends"),
        b.const(_np.asarray([axis], _np.int64), "axes"),
    ], [out], name=name)


@_conv("expand_dims")
def _c_expand(b, name, ins, attrs, out):
    ax = b.const(_np.asarray([int(attrs["axis"])], _np.int64), "axes")
    b.node("Unsqueeze", [ins[0], ax], [out], name=name)


@_conv("squeeze")
def _c_squeeze(b, name, ins, attrs, out):
    axis = attrs.get("axis")
    if axis is None:
        b.node("Squeeze", ins[:1], [out], name=name)
    else:
        axes = [axis] if isinstance(axis, int) else list(axis)
        ax = b.const(_np.asarray(axes, _np.int64), "axes")
        b.node("Squeeze", [ins[0], ax], [out], name=name)


def _reduce(onnx_op, axes_as_input=False):
    def f(b, name, ins, attrs, out):
        axis = attrs.get("axis")
        keep = int(attrs.get("keepdims", False))
        axes = None if axis is None else \
            ([axis] if isinstance(axis, int) else list(axis))
        if axes_as_input:
            extra = [] if axes is None else \
                [b.const(_np.asarray(axes, _np.int64), "axes")]
            b.node(onnx_op, [ins[0]] + extra, [out], name=name,
                   keepdims=keep)
        else:
            b.node(onnx_op, ins[:1], [out], name=name, keepdims=keep,
                   axes=axes)

    return f


_CONVERTERS["mean"] = _reduce("ReduceMean")
_CONVERTERS["max"] = _reduce("ReduceMax")
_CONVERTERS["min"] = _reduce("ReduceMin")
_CONVERTERS["prod"] = _reduce("ReduceProd")
_CONVERTERS["sum"] = _reduce("ReduceSum", axes_as_input=True)


@_conv("Embedding")
def _c_embedding(b, name, ins, attrs, out):
    idx = f"{name}_idx64"
    b.node("Cast", [ins[0]], [idx], to=P.TensorProto.INT64)
    b.node("Gather", [ins[1], idx], [out], name=name)


@_conv("LayerNorm")
def _c_layernorm(b, name, ins, attrs, out):
    b.node("LayerNormalization", ins[:3], [out], name=name,
           axis=int(attrs.get("axis", -1)),
           epsilon=float(attrs.get("eps", 1e-5)))


def export_model(sym, params, input_shapes=None, input_types=None,
                 onnx_file_path="model.onnx", verbose=False,
                 dynamic=False):
    """Export a Symbol + params to an ONNX ModelProto file; returns the
    path (reference ``mx.onnx.export_model`` signature).

    ``params`` maps name -> NDArray/ndarray; the reference's
    'arg:'/'aux:' prefixes are accepted and stripped. ``input_shapes``:
    list of shapes for the free (non-param) variables, in
    ``list_arguments`` order."""
    from ..symbol.symbol import Symbol

    if not isinstance(sym, Symbol):
        raise MXNetError("export_model expects a Symbol")
    pvals: Dict[str, _np.ndarray] = {}
    for k, v in (params or {}).items():
        if k.startswith(("arg:", "aux:")):
            k = k[4:]
        pvals[k] = _np.asarray(v.asnumpy() if hasattr(v, "asnumpy") else v)

    b = _Builder()
    b.params = {k: v.shape for k, v in pvals.items()}
    order: List = []
    seen = set()
    # iterative DFS: deep chains (unrolled sequences, 100+-layer nets)
    # overflow Python recursion otherwise. Indexed views of a
    # multi-output node share its name: dedupe by (name, is_var) so one
    # ONNX node is emitted per symbol node.
    stack = [(sym, False)]
    while stack:
        s, expanded = stack.pop()
        key = (s._name, s._is_var())
        if expanded:
            if key not in seen:
                seen.add(key)
                order.append(s)
            continue
        if key in seen:
            continue
        stack.append((s, True))
        for i in reversed(s._inputs):
            stack.append((i, False))

    free_vars: List[str] = []
    for s in order:
        if s._is_var():
            if s._name not in pvals:
                free_vars.append(s._name)
        elif s._op is None:
            raise MXNetError("ONNX export: group symbols are not a graph")

    if dynamic:
        raise MXNetError(
            "ONNX export: dynamic axes are not supported; export with "
            "concrete input_shapes")
    shapes = {}
    if input_shapes is not None:
        for n, shp in zip(free_vars, input_shapes):
            shapes[n] = shp
    elems = {}
    if input_types is not None:
        types = input_types if isinstance(input_types, (list, tuple)) \
            else [input_types] * len(free_vars)
        for n, t in zip(free_vars, types):
            elems[n] = _np_to_elem(t)

    for s in order:
        if s._is_var() or s._op is None:
            continue
        conv = _CONVERTERS.get(s._op)
        if conv is None:
            raise MXNetError(
                f"ONNX export: no converter for op {s._op!r} "
                f"(node {s._name}); supported: "
                f"{sorted(_CONVERTERS)}"
            )
        ins = []
        for i in s._inputs:
            if i._out_index not in (None, 0):
                raise MXNetError(
                    f"ONNX export: {s._name} consumes output "
                    f"{i._out_index} of {i._name}; only primary outputs "
                    "export (aux outputs are training-only state)"
                )
            ins.append(i._name)
        conv(b, s._name, ins, s._attrs, s._name)

    # initializers and graph inputs AFTER conversion: only values some
    # emitted node actually consumes (loss heads drop their label input;
    # fix_gamma replaces its gamma — neither may surface in the file)
    used = {sym._name}  # a bare-variable head is its own output
    for n in b.nodes:
        used.update(n.input)
    for name in list(dict.fromkeys(  # preserve DAG order
            s._name for s in order if s._is_var())):
        if name in pvals:
            if name in used and name not in b.replaced:
                b.initializers.append(_tensor(name, pvals[name]))
    graph_inputs: List[P.ValueInfoProto] = []
    for n in free_vars:
        if n in used:
            graph_inputs.append(
                _vinfo(n, shapes.get(n), elems.get(n, P.TensorProto.FLOAT)))

    m = P.ModelProto()
    m.ir_version = 8
    m.producer_name = "mxnet_tpu"
    m.producer_version = "0.4"
    op = m.opset_import.add()
    op.version = OPSET
    g = m.graph
    g.name = sym._name
    g.node.extend(b.nodes)
    g.initializer.extend(b.initializers)
    g.input.extend(graph_inputs)
    if sym._out_index not in (None, 0):
        raise MXNetError("ONNX export: head must be output 0 of its node")
    g.output.append(_vinfo(sym._name))
    with open(onnx_file_path, "wb") as f:
        f.write(m.SerializeToString())
    if verbose:
        print(f"exported {len(b.nodes)} nodes, "
              f"{len(b.initializers)} initializers -> {onnx_file_path}")
    return onnx_file_path
