"""ONNX -> mx import (reference: ``mx.onnx.import_model`` /
``onnx2mx`` converters [unverified]).

Parses a ModelProto (any producer — the vendored schema subset reads the
standard wire format) into a Symbol graph plus arg/aux param dicts, the
reference's ``(sym, arg_params, aux_params)`` contract, for the operator
subset the exporter emits (CNN/MLP/attention-adjacent ops).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as _np

from ..base import MXNetError
from . import onnx_subset_pb2 as P

_NP_DTYPE = {
    P.TensorProto.FLOAT: _np.float32,
    P.TensorProto.DOUBLE: _np.float64,
    P.TensorProto.FLOAT16: _np.float16,
    P.TensorProto.INT32: _np.int32,
    P.TensorProto.INT64: _np.int64,
    P.TensorProto.INT8: _np.int8,
    P.TensorProto.UINT8: _np.uint8,
    P.TensorProto.BOOL: _np.bool_,
}


def _to_np(t: P.TensorProto) -> _np.ndarray:
    dt = _NP_DTYPE.get(t.data_type)
    if dt is None:
        raise MXNetError(f"ONNX import: tensor dtype {t.data_type}")
    shape = tuple(t.dims)
    if t.raw_data:
        return _np.frombuffer(t.raw_data, dtype=dt).reshape(shape).copy()
    if t.float_data:
        return _np.asarray(t.float_data, dt).reshape(shape)
    if t.int64_data:
        return _np.asarray(t.int64_data, dt).reshape(shape)
    if t.int32_data:
        return _np.asarray(t.int32_data, dt).reshape(shape)
    return _np.zeros(shape, dt)


def _attrs(node: P.NodeProto) -> dict:
    out = {}
    for a in node.attribute:
        if a.type == P.AttributeProto.INT:
            out[a.name] = int(a.i)
        elif a.type == P.AttributeProto.FLOAT:
            out[a.name] = float(a.f)
        elif a.type == P.AttributeProto.STRING:
            out[a.name] = a.s.decode()
        elif a.type == P.AttributeProto.INTS:
            out[a.name] = tuple(int(x) for x in a.ints)
        elif a.type == P.AttributeProto.FLOATS:
            out[a.name] = tuple(float(x) for x in a.floats)
        elif a.type == P.AttributeProto.TENSOR:
            out[a.name] = _to_np(a.t)
    return out


def _pads(a, nd):
    p = a.get("pads")
    if not p:
        return (0,) * nd
    begin, end = p[:nd], p[nd:]
    if tuple(begin) != tuple(end):
        raise MXNetError("ONNX import: asymmetric pads unsupported")
    return tuple(begin)


def import_model(onnx_file_path):
    """-> (sym, arg_params, aux_params), the reference contract."""
    from .. import symbol as sym_mod
    from ..ndarray import array as nd_array

    m = P.ModelProto()
    with open(onnx_file_path, "rb") as f:
        m.ParseFromString(f.read())
    g = m.graph

    inits: Dict[str, _np.ndarray] = {t.name: _to_np(t)
                                     for t in g.initializer}
    values: Dict[str, object] = {}
    aux_names = set()
    # consts consumed structurally (Reshape shapes, Slice starts...)
    structural = set()

    for vi in g.input:
        if vi.name not in inits:
            values[vi.name] = sym_mod.var(vi.name)

    def var_for(name):
        if name not in values:
            if name not in inits:
                raise MXNetError(f"ONNX import: undefined value {name!r}")
            values[name] = sym_mod.var(name)
        return values[name]

    def const_ints(name):
        if name not in inits:
            raise MXNetError(
                f"ONNX import: {name!r} must be a constant initializer")
        structural.add(name)
        return [int(x) for x in _np.asarray(inits[name]).reshape(-1)]

    def const_floats(name):
        if name not in inits:
            raise MXNetError(
                f"ONNX import: {name!r} must be a constant initializer")
        structural.add(name)
        return [float(x) for x in _np.asarray(inits[name]).reshape(-1)]

    S = sym_mod

    for node in g.node:
        op = node.op_type
        a = _attrs(node)
        ins = list(node.input)
        out = node.output[0]

        def I(k=0):  # noqa: E743 - local helper
            return var_for(ins[k])

        if op == "Conv":
            kernel = a["kernel_shape"]
            nd = len(kernel)
            args = [I(0), I(1)] + ([I(2)] if len(ins) > 2 else [])
            res = S.Convolution(
                *args, kernel=tuple(kernel),
                stride=tuple(a.get("strides", (1,) * nd)),
                dilate=tuple(a.get("dilations", (1,) * nd)),
                pad=_pads(a, nd), num_group=a.get("group", 1),
                no_bias=len(ins) <= 2,
                num_filter=int(inits[ins[1]].shape[0])
                if ins[1] in inits else 0)
        elif op == "Gemm":
            if a.get("transB", 0) != 1 or a.get("transA", 0) != 0:
                raise MXNetError("ONNX import: Gemm needs transB=1")
            if a.get("alpha", 1.0) != 1.0:
                raise MXNetError("ONNX import: Gemm alpha != 1 unsupported")
            # beta only scales the C operand; with two inputs (no bias,
            # the exporter emits beta=0.0 for no_bias FullyConnected)
            # any beta value is irrelevant.
            if len(ins) > 2 and a.get("beta", 1.0) != 1.0:
                raise MXNetError("ONNX import: Gemm beta != 1 unsupported")
            args = [I(0), I(1)] + ([I(2)] if len(ins) > 2 else [])
            num_hidden = int(inits[ins[1]].shape[0]) \
                if ins[1] in inits else 0
            res = S.FullyConnected(*args, num_hidden=num_hidden,
                                   no_bias=len(ins) <= 2, flatten=False)
        elif op == "MatMul":
            res = S.dot(I(0), I(1))
        elif op == "BatchNormalization":
            aux_names.update(ins[3:5])
            res = S.BatchNorm(
                I(0), I(1), I(2), I(3), I(4),
                eps=a.get("epsilon", 1e-5),
                momentum=a.get("momentum", 0.9), fix_gamma=False,
                use_global_stats=True)[0]
        elif op in ("Relu", "Sigmoid", "Tanh", "Softplus", "Softsign"):
            act = {"Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
                   "Softplus": "softrelu", "Softsign": "softsign"}[op]
            res = S.Activation(I(0), act_type=act)
        elif op == "LeakyRelu":
            res = S.LeakyReLU(I(0), slope=a.get("alpha", 0.01))
        elif op == "PRelu":
            res = S.LeakyReLU(I(0), I(1), act_type="prelu")
        elif op in ("MaxPool", "AveragePool"):
            kernel = a["kernel_shape"]
            nd = len(kernel)
            res = S.Pooling(
                I(0), kernel=tuple(kernel),
                pool_type="max" if op == "MaxPool" else "avg",
                stride=tuple(a.get("strides", (1,) * nd)),
                pad=_pads(a, nd),
                pooling_convention="full" if a.get("ceil_mode") else "valid",
                # ONNX spec default is 0: padding EXCLUDED from the mean
                count_include_pad=bool(a.get("count_include_pad", 0)))
        elif op in ("GlobalMaxPool", "GlobalAveragePool"):
            res = S.Pooling(
                I(0), global_pool=True, kernel=(1, 1),
                pool_type="max" if op == "GlobalMaxPool" else "avg")
        elif op == "Flatten":
            if a.get("axis", 1) != 1:
                raise MXNetError("ONNX import: Flatten axis != 1")
            res = S.Flatten(I(0))
        elif op == "Reshape":
            res = S.Reshape(I(0), shape=tuple(const_ints(ins[1])))
        elif op == "Concat":
            res = S.concat(*[var_for(n) for n in ins],
                           dim=a.get("axis", 1))
        elif op in ("Add", "Sub", "Mul", "Div", "Pow"):
            name = {"Add": "broadcast_add", "Sub": "broadcast_sub",
                    "Mul": "broadcast_mul", "Div": "broadcast_div",
                    "Pow": "broadcast_power"}[op]
            res = getattr(S, name)(I(0), I(1))
        elif op == "Sum":
            res = S.add_n(*[var_for(n) for n in ins])
        elif op in ("Softmax", "LogSoftmax"):
            fn = S.softmax if op == "Softmax" else S.log_softmax
            res = fn(I(0), axis=a.get("axis", -1))
        elif op == "Identity" or op == "Dropout":
            res = S.identity(I(0))
        elif op == "Transpose":
            perm = a.get("perm")
            res = S.transpose(I(0), axes=tuple(perm) if perm else None)
        elif op == "Clip":
            lo = const_floats(ins[1])[0] if len(ins) > 1 and ins[1] else None
            hi = const_floats(ins[2])[0] if len(ins) > 2 and ins[2] else None
            res = S.clip(I(0), a_min=a.get("min", lo),
                         a_max=a.get("max", hi))
        elif op == "Slice":
            starts = const_ints(ins[1])
            ends = const_ints(ins[2])
            axes = const_ints(ins[3]) if len(ins) > 3 and ins[3] else \
                list(range(len(starts)))
            if len(ins) > 4 and ins[4]:
                steps = const_ints(ins[4])
                if any(st != 1 for st in steps):
                    raise MXNetError(
                        "ONNX import: strided Slice (steps != 1) "
                        "unsupported")
            res = var_for(ins[0])
            for st, en, ax in zip(starts, ends, axes):
                en_v = None if en >= 2 ** 62 else en
                res = S.slice_axis(res, axis=ax, begin=st, end=en_v)
        elif op == "Unsqueeze":
            res = var_for(ins[0])
            for ax in sorted(const_ints(ins[1])):
                res = S.expand_dims(res, axis=ax)
        elif op == "Squeeze":
            axes = const_ints(ins[1]) if len(ins) > 1 else None
            res = S.squeeze(I(0), axis=tuple(axes) if axes else None)
        elif op in ("ReduceMean", "ReduceMax", "ReduceMin", "ReduceProd",
                    "ReduceSum"):
            fn = {"ReduceMean": S.mean, "ReduceMax": S.max,
                  "ReduceMin": S.min, "ReduceProd": S.prod,
                  "ReduceSum": S.sum}[op]
            if op == "ReduceSum" and len(ins) > 1:
                axes = tuple(const_ints(ins[1]))
            else:
                axes = a.get("axes")
                axes = tuple(axes) if axes is not None else None
            res = fn(I(0), axis=axes, keepdims=bool(a.get("keepdims", 1)))
        elif op == "Cast":
            to = a.get("to")
            np_dt = _NP_DTYPE.get(to)
            if np_dt is None:
                raise MXNetError(
                    f"ONNX import: Cast target dtype {to} unsupported")
            res = S.cast(I(0), dtype=_np.dtype(np_dt).name)
        elif op == "Gather":
            if a.get("axis", 0) != 0:
                raise MXNetError(
                    "ONNX import: Gather axis != 0 unsupported")
            res = S.Embedding(
                I(1), I(0),
                input_dim=int(inits[ins[0]].shape[0])
                if ins[0] in inits else 0,
                output_dim=int(inits[ins[0]].shape[-1])
                if ins[0] in inits else 0)
        elif op == "LayerNormalization":
            res = S.LayerNorm(I(0), I(1), I(2),
                              axis=a.get("axis", -1),
                              eps=a.get("epsilon", 1e-5))
        else:
            raise MXNetError(f"ONNX import: unsupported op {op!r}")
        values[out] = res
        for extra in node.output[1:]:
            if extra:
                raise MXNetError(
                    f"ONNX import: multi-output node {op} unsupported")

    outs = [values[o.name] for o in g.output]
    sym = outs[0] if len(outs) == 1 else sym_mod.Group(outs)
    arg_params, aux_params = {}, {}
    for name, arr in inits.items():
        if name in structural:
            continue
        (aux_params if name in aux_names else arg_params)[name] = \
            nd_array(arr)
    return sym, arg_params, aux_params
