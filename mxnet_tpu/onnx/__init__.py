"""ONNX interchange (reference: ``python/mxnet/onnx`` mx2onnx converters
[unverified]).

Availability-gated: this environment ships no ``onnx`` package (zero
egress), so converters cannot build or validate real ModelProto graphs and
are NOT shipped half-written. The deployment-interchange role the
reference filled with ONNX is served first-class by the StableHLO export
path (``HybridBlock.export`` -> ``SymbolBlock.imports`` over
``jax.export``), which round-trips compiled graphs without Python model
code and without an intermediate op-by-op converter layer.

API surface matches the reference entry points so callers get a precise
error (with the supported alternative) rather than an AttributeError.
"""

from __future__ import annotations

from ..base import MXNetError

__all__ = ["export_model", "import_model", "is_available"]

_GATE_MSG = (
    "the 'onnx' package is not installed in this environment, so ONNX "
    "{what} is unavailable; for compiled-graph deployment use "
    "HybridBlock.export (StableHLO via jax.export), which "
    "SymbolBlock.imports reloads"
)


def is_available() -> bool:
    try:
        import onnx  # noqa: F401

        return True
    except ImportError:
        return False


def export_model(sym, params, input_shapes=None, input_types=None,
                 onnx_file_path="model.onnx", **kwargs):
    """Reference: ``mx.onnx.export_model`` — gated on the onnx package."""
    raise MXNetError(_GATE_MSG.format(what="export"))


def import_model(onnx_file_path):
    """Reference: ``mx.onnx.import_model`` — gated on the onnx package."""
    raise MXNetError(_GATE_MSG.format(what="import"))
