"""ONNX interchange (reference: ``python/mxnet/onnx`` mx2onnx/onnx2mx
converters [unverified]).

Round 4: real converters. The environment ships no ``onnx`` package, but
ONNX is a protobuf wire format — the vendored schema subset
(``onnx_subset.proto``, standard field numbers, compiled with the
system protoc) serializes/parses ModelProto files any ONNX runtime
understands. ``export_model`` walks the Symbol DAG emitting op-by-op
converted nodes + initializers; ``import_model`` parses a ModelProto
back into ``(sym, arg_params, aux_params)``. Round-trip parity is
pinned in ``tests/test_onnx.py``.

StableHLO export (``HybridBlock.export`` over ``jax.export``) remains
the native compiled-graph deployment path; ONNX is the cross-framework
interchange the reference offered.
"""

from __future__ import annotations

__all__ = ["export_model", "import_model", "is_available"]


def is_available() -> bool:
    try:
        from . import onnx_subset_pb2  # noqa: F401

        return True
    except Exception:
        return False


def export_model(sym, params, input_shapes=None, input_types=None,
                 onnx_file_path="model.onnx", **kwargs):
    """Reference: ``mx.onnx.export_model(sym, params, in_shapes,
    in_types, onnx_file)`` -> path of the written ModelProto."""
    from .mx2onnx import export_model as _impl

    return _impl(sym, params, input_shapes=input_shapes,
                 input_types=input_types, onnx_file_path=onnx_file_path,
                 **kwargs)


def import_model(onnx_file_path):
    """Reference: ``mx.onnx.import_model`` ->
    (sym, arg_params, aux_params)."""
    from .onnx2mx import import_model as _impl

    return _impl(onnx_file_path)
