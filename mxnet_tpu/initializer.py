"""Weight initializers (reference: ``python/mxnet/initializer.py`` [unverified]).

Same registry-by-name design as the reference (``@register`` + ``create``),
but sampling goes through jax's counter-based RNG (``mxnet_tpu.random``)
instead of a stateful per-device generator: each ``InitDesc`` draw folds a
fresh subkey so initialization is reproducible under ``mx.random.seed``.
"""

from __future__ import annotations

import json
import math
import re
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as _np

from .base import MXNetError
from . import random as _random
from .ndarray.ndarray import NDArray

__all__ = [
    "InitDesc",
    "Initializer",
    "register",
    "create",
    "Zero",
    "One",
    "Constant",
    "Uniform",
    "Normal",
    "Orthogonal",
    "Xavier",
    "MSRAPrelu",
    "Bilinear",
    "LSTMBias",
    "Mixed",
    "Load",
]

_REGISTRY: Dict[str, type] = {}


def register(klass):
    """Register an initializer class under its lower-cased name."""
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs) -> "Initializer":
    if isinstance(name, Initializer):
        return name
    if callable(name):
        return name
    if name is None:
        return Uniform()
    key = str(name).lower()
    if key not in _REGISTRY:
        raise MXNetError(f"unknown initializer {name!r}")
    return _REGISTRY[key](**kwargs)


class InitDesc(str):
    """Parameter-name string carrying init attrs (reference: ``InitDesc``)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base initializer. Subclasses implement ``_init_weight(name, arr)``."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose = False
        self._print_func = None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose = verbose
        self._print_func = print_func or (lambda x: None)
        return self

    def dumps(self) -> str:
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __eq__(self, other):
        return isinstance(other, self.__class__) and self._kwargs == getattr(
            other, "_kwargs", None
        )

    def __hash__(self):
        return hash(self.__class__.__name__)

    def __call__(self, desc, arr: NDArray):
        if not isinstance(desc, InitDesc):
            desc = InitDesc(desc)
        init_name = desc.attrs.get("__init__", "")
        if init_name:
            create(json.loads(init_name)[0], **json.loads(init_name)[1])._init_weight(
                desc, arr
            )
            return
        name = desc.lower()
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(desc, arr)
        else:
            self._init_default(desc, arr)
        if self._verbose and self._print_func:
            self._print_func(f"initialized {desc}")

    # ---- per-suffix defaults (match reference behavior)
    def _init_zero(self, _, arr):
        arr._rebind(jnp.zeros(arr.shape, arr.data.dtype))

    def _init_one(self, _, arr):
        arr._rebind(jnp.ones(arr.shape, arr.data.dtype))

    def _init_bias(self, _, arr):
        self._init_zero(_, arr)

    def _init_gamma(self, _, arr):
        self._init_one(_, arr)

    def _init_beta(self, _, arr):
        self._init_zero(_, arr)

    def _init_weight(self, name, arr):  # pragma: no cover - abstract
        raise NotImplementedError

    def _init_default(self, name, arr):
        self._init_weight(name, arr)


def _key():
    return _random.next_key()


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        arr._rebind(jnp.zeros(arr.shape, arr.data.dtype))


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        arr._rebind(jnp.ones(arr.shape, arr.data.dtype))


# reference registers these under both names
_REGISTRY["zeros"] = Zero
_REGISTRY["ones"] = One


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        val = self.value
        if isinstance(val, NDArray):
            val = val.data
        arr._rebind(jnp.broadcast_to(jnp.asarray(val, arr.data.dtype), arr.shape))


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        arr._rebind(
            jax.random.uniform(
                _key(), arr.shape, arr.data.dtype, -self.scale, self.scale
            )
        )


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        arr._rebind(
            self.sigma * jax.random.normal(_key(), arr.shape, arr.data.dtype)
        )


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = jax.random.uniform(_key(), (nout, nin), minval=-1.0, maxval=1.0)
        else:
            tmp = jax.random.normal(_key(), (nout, nin))
        u, _s, v = jnp.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        arr._rebind(self.scale * q.reshape(arr.shape).astype(arr.data.dtype))


@register
class Xavier(Initializer):
    """Xavier/Glorot (reference default for Gluon weight init via string)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(
            rnd_type=rnd_type, factor_type=factor_type, magnitude=magnitude
        )
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        if len(shape) < 2:
            raise MXNetError(
                f"Xavier initializer needs >=2D weight, got {shape} for {name}"
            )
        hw_scale = float(_np.prod(shape[2:])) if len(shape) > 2 else 1.0
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError(f"invalid factor_type {self.factor_type}")
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            out = jax.random.uniform(
                _key(), shape, arr.data.dtype, -scale, scale
            )
        elif self.rnd_type == "gaussian":
            out = scale * jax.random.normal(_key(), shape, arr.data.dtype)
        else:
            raise MXNetError(f"invalid rnd_type {self.rnd_type}")
        arr._rebind(out)


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel for Deconvolution."""

    def _init_weight(self, _, arr):
        shape = arr.shape
        weight = _np.zeros(int(_np.prod(shape)), dtype="float32")
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(_np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr._rebind(jnp.asarray(weight.reshape(shape), arr.data.dtype))


@register
class LSTMBias(Initializer):
    """Forget-gate bias = 1 trick (reference: ``LSTMBias``)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, _, arr):
        b = _np.zeros(arr.shape, dtype="float32")
        num_hidden = arr.shape[0] // 4
        b[num_hidden : 2 * num_hidden] = self.forget_bias
        arr._rebind(jnp.asarray(b, arr.data.dtype))

    _init_default = _init_weight
    _init_bias = _init_weight


@register
class Mixed(Initializer):
    """Dispatch by regex over parameter names (reference: ``Mixed``)."""

    def __init__(self, patterns, initializers):
        super().__init__()
        if len(patterns) != len(initializers):
            raise MXNetError("patterns and initializers length mismatch")
        self.map = [(re.compile(p), create(i)) for p, i in zip(patterns, initializers)]

    def __call__(self, desc, arr):
        for prog, init in self.map:
            if prog.match(str(desc)):
                # the pattern IS the dispatch — bypass suffix heuristics
                init._init_default(desc, arr)
                return
        raise MXNetError(
            f"parameter {desc} did not match any pattern; add '.*' as a catchall"
        )


@register
class Load:
    """Init from a dict of arrays, falling back to ``default_init``."""

    def __init__(self, param, default_init=None, verbose=False):
        self.param = {
            k.replace("arg:", "").replace("aux:", ""): v for k, v in param.items()
        }
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            src = self.param[name]
            if tuple(src.shape) != tuple(arr.shape):
                raise MXNetError(
                    f"shape mismatch loading {name}: saved {src.shape} vs {arr.shape}"
                )
            arr._rebind(jnp.asarray(src.data if isinstance(src, NDArray) else src))
        else:
            if self.default_init is None:
                raise MXNetError(f"cannot init {name}: not found and no default")
            self.default_init(name, arr)


class init:  # namespace alias so `mx.init.Xavier()` works like the reference
    pass


for _n, _k in list(_REGISTRY.items()):
    setattr(init, _k.__name__, _k)
init.InitDesc = InitDesc
init.create = create
init.register = register
