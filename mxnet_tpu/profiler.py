"""Profiler facade (reference: ``python/mxnet/profiler.py`` over
``src/profiler/profiler.cc`` [unverified]).

The reference instrumented every engine op push and dumped Chrome-trace
JSON. On TPU the equivalent telemetry comes from XLA's profiler (XProf):
``jax.profiler`` emits a trace viewable in TensorBoard/Perfetto covering
compiled-program timelines, HBM usage, and per-op device time. This module
keeps the reference's API shape (set_config/start/stop/dump + scopes) over
that machinery.

Host-side aggregate per-call stats live in the ``mx.telemetry`` metrics
registry (the ``op/`` histogram family) — ONE telemetry spine: Scopes feed
the same registry the trainer/kvstore/dataloader instrumentation uses, so
``mx.telemetry.report()`` and ``profiler.dumps()`` read consistent data,
and ``profiler.dump()`` merges the registry aggregates with any buffered
telemetry spans into one Chrome trace.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Dict, Optional

import jax

from . import telemetry as _telemetry
from .base import MXNetError

__all__ = [
    "set_config",
    "start",
    "stop",
    "pause",
    "resume",
    "dump",
    "dumps",
    "set_state",
    "Scope",
    "Task",
    "Frame",
    "Event",
    "Counter",
    "Marker",
]

_CONFIG = {
    "filename": "profile.json",
    "profile_all": False,
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_memory": True,
    "profile_api": True,
    "aggregate_stats": False,
}
_STATE = {"running": False, "dir": None}
_LOCK = threading.Lock()
_OP_PREFIX = "op/"  # registry family holding per-op aggregate stats


def set_config(**kwargs):
    """Reference: ``mx.profiler.set_config`` (filename, profile_all, …)."""
    for k, v in kwargs.items():
        _CONFIG[k] = v


def set_state(state="stop", profile_process="worker"):
    if state == "run":
        start()
    elif state == "stop":
        stop()
    else:
        raise MXNetError(f"invalid profiler state {state!r}")


def start(profile_process="worker"):
    """Start an XProf trace (plus host aggregate stats)."""
    if _STATE["running"]:
        return
    trace_dir = os.path.splitext(_CONFIG["filename"])[0] + "_xplane"
    _STATE["dir"] = trace_dir
    try:
        jax.profiler.start_trace(trace_dir)
    except Exception:
        # tracing may be unsupported on some backends; keep host stats only
        _STATE["dir"] = None
    _STATE["running"] = True


def stop(profile_process="worker"):
    if not _STATE["running"]:
        return
    if _STATE["dir"] is not None:
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
    _STATE["running"] = False


def pause(profile_process="worker"):
    stop()


def resume(profile_process="worker"):
    start()


def record_host_op(name: str, seconds: float):
    """Hook used by the imperative layer when aggregate stats are enabled.

    Rebased onto the telemetry registry: each op is a rolling histogram
    under ``op/{name}`` (cumulative count/sum preserved), so the same
    spine serves ``dumps()``, ``mx.telemetry.report()`` and the bench
    schema."""
    _telemetry.registry().histogram(_OP_PREFIX + name).observe(seconds)


def _op_rows():
    """(name, count, total_s) rows from the registry's op/ family."""
    hists = _telemetry.registry().histograms_with_prefix(_OP_PREFIX)
    return [(name[len(_OP_PREFIX):], h.count, h.sum)
            for name, h in hists.items()]


def dumps(reset=False) -> str:
    """Aggregate per-op stats table (reference: ``mx.profiler.dumps``)."""
    rows = sorted(_op_rows(), key=lambda r: -r[2])
    lines = [f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}{'Avg(us)':>10}"]
    for name, count, total in rows:
        lines.append(
            f"{name:<40}{count:>8}{total * 1e3:>12.2f}"
            f"{total / max(count, 1) * 1e6:>10.1f}"
        )
    if reset:
        _telemetry.registry().clear(prefix=_OP_PREFIX)
    return "\n".join(lines)


def dump(finished=True, profile_process="worker"):
    """Write host-side aggregate stats (plus any buffered telemetry spans)
    as ONE Chrome-trace JSON; the XProf trace directory (if any) sits next
    to it for TensorBoard."""
    stop()
    events = []
    ts = 0
    for name, count, total in _op_rows():
        events.append(
            {
                "name": name,
                "ph": "X",
                "ts": ts,
                "dur": total * 1e6,
                "pid": 0,
                "tid": 0,
                "args": {"calls": count},
            }
        )
        ts += total * 1e6
    log = _telemetry._LOG
    if log is not None:
        events.extend(log.chrome_events())
    with open(_CONFIG["filename"], "w") as f:
        json.dump({"traceEvents": events}, f)


class Scope:
    """Annotation scope; shows up in the XProf timeline (reference: profiler
    scopes / NVTX ranges)."""

    def __init__(self, name="<unk>", append_mode=True):
        self._name = name
        self._ctx = None

    def __enter__(self):
        self._ctx = jax.profiler.TraceAnnotation(self._name)
        self._ctx.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._ctx.__exit__(*exc)
        record_host_op(self._name, time.perf_counter() - self._t0)
        return False


class Task(Scope):
    def __init__(self, domain=None, name="<unk>"):
        super().__init__(name)


class Frame(Scope):
    def __init__(self, domain=None, name="<unk>"):
        super().__init__(name)


class Event(Scope):
    def __init__(self, name="<unk>"):
        super().__init__(name)


class Counter:
    def __init__(self, domain=None, name="<unk>", value=None):
        self._name = name
        self._value = value or 0

    def set_value(self, value):
        self._value = value

    def increment(self, delta=1):
        self._value += delta

    def decrement(self, delta=1):
        self._value -= delta


class Marker:
    def __init__(self, domain=None, name="<unk>"):
        self._name = name

    def mark(self, scope="process"):
        record_host_op(f"marker:{self._name}", 0.0)


atexit.register(stop)
