"""Gluon: imperative/hybrid high-level API (reference:
``python/mxnet/gluon/`` [unverified])."""

from . import parameter
from .parameter import Parameter, Constant, ParameterDict
from . import block
from .block import Block, HybridBlock, SymbolBlock, CachedOp
from . import nn
from . import loss
from . import trainer
from .trainer import Trainer
from . import utils
from . import data
from . import rnn
from . import model_zoo
from . import contrib

__all__ = [
    "parameter", "Parameter", "Constant", "ParameterDict",
    "block", "Block", "HybridBlock", "SymbolBlock", "CachedOp",
    "nn", "loss", "trainer", "Trainer", "utils", "data", "rnn",
    "model_zoo", "contrib",
]
