"""Basic layers: Sequential, Dense, Dropout, BatchNorm, Embedding, …
(reference: ``python/mxnet/gluon/nn/basic_layers.py`` [unverified])."""

from __future__ import annotations

import numpy as _np

from ...base import MXNetError
from ... import autograd
from ..block import Block, HybridBlock
from .activations import Activation

__all__ = [
    "Sequential",
    "HybridSequential",
    "Dense",
    "Dropout",
    "Embedding",
    "BatchNorm",
    "InstanceNorm",
    "LayerNorm",
    "GroupNorm",
    "Flatten",
    "Lambda",
    "HybridLambda",
]


class Sequential(Block):
    """Stack of Blocks executed sequentially."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        if self._children and all(
            isinstance(c, HybridBlock) for c in self._children.values()
        ):
            import warnings

            warnings.warn(
                f"All children of {type(self).__name__} are HybridBlocks; "
                "consider HybridSequential to allow staging.",
                stacklevel=2,
            )
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Stack of HybridBlocks, stageable as one XLA program."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)
        self._clear_cached_op()

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer: ``out = act(dot(x, W.T) + b)``.

    Reference: Gluon ``Dense`` over ``FullyConnected``
    (``src/operator/nn/fully_connected.cc`` [unverified]). Weight layout is
    (units, in_units) like the reference, so checkpoints map 1:1.
    """

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._flatten = flatten
        self._units = units
        self._in_units = in_units
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), init=weight_initializer,
                dtype=dtype, allow_deferred_init=True,
            )
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), init=bias_initializer,
                    dtype=dtype, allow_deferred_init=True,
                )
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def infer_shape(self, x, *args):
        in_units = (
            int(_np.prod(x.shape[1:])) if self._flatten else int(x.shape[-1])
        )
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        act = F.FullyConnected(
            x, weight, bias, no_bias=bias is None, num_hidden=self._units,
            flatten=self._flatten,
        )
        if self.act is not None:
            act = self.act(act)
        return act

    def __repr__(self):
        shape = self.weight.shape
        return (
            f"Dense({shape[1] if shape[1] else None} -> {shape[0]}, "
            f"{'linear' if self.act is None else self.act._act_type})"
        )


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate > 0:
            return F.Dropout(x, p=self._rate, axes=self._axes)
        return F.identity(x)

    def __repr__(self):
        return f"Dropout(p = {self._rate}, axes={self._axes})"


def _sparse_embedding_apply(x, weight_param, input_dim, output_dim):
    """Eager sparse-grad embedding: gather forward; backward writes a
    compressed row-sparse gradient (token rows, output cotangents) into
    ``weight_param.grad`` DIRECTLY — the weight deliberately does not ride
    the tape, so the dense (V, D) scatter is never built (reference
    ``Embedding(sparse_grad=True)`` semantics; the sparse optimizer
    updates then touch live rows only)."""
    import jax.numpy as jnp

    from ...ndarray.ndarray import NDArray
    from ...ndarray.sparse import RowSparseNDArray

    import jax
    import numpy as _np2

    weight_nd = weight_param.data()

    class _Apply(autograd.Function):
        def forward(self, x_nd, w_nd):
            ids = x_nd.data.astype(jnp.int32)
            return NDArray(jnp.take(w_nd.data, ids, axis=0))

        def backward(self, dout):
            ids = x.data.astype(jnp.int32).reshape(-1)
            vals = dout.data.reshape(-1, output_dim)
            g = RowSparseNDArray.from_pair(
                ids, vals, (input_dim, output_dim)
            )
            # 'write' semantics reset PER BACKWARD TRAVERSAL (epoch stamp
            # bumped by autograd.backward): contributions from multiple
            # uses of this weight inside one traversal accumulate, a new
            # traversal overwrites, and a recorded forward whose backward
            # never runs cannot destroy a pending gradient
            epoch = autograd._BACKWARD_EPOCH[0]
            prev = weight_nd._grad
            same_pass = (
                isinstance(prev, RowSparseNDArray) and prev._pair
                and getattr(prev, "_rs_epoch", None) == epoch
            )
            accumulate = same_pass or weight_param.grad_req == "add"
            if accumulate and isinstance(prev, RowSparseNDArray) \
                    and prev._pair:
                g = prev + g
            g._rs_epoch = epoch
            weight_nd._grad = g
            # float0 cotangents: the tape must NOT accumulate a dense
            # gradient for the weight (that's the whole point) — the
            # compressed pair was just written into weight.grad above
            return (_np2.zeros(x.shape, jax.dtypes.float0),
                    _np2.zeros(weight_nd.shape, jax.dtypes.float0))

    return _Apply()(x, weight_nd)


class Embedding(HybridBlock):
    """Index -> dense vector lookup (reference: ``Embedding`` over the
    ``Embedding`` op = gather rows of the weight)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        # sparse_grad: eager backward writes a COMPRESSED row-sparse
        # gradient (token rows, output cotangents) into weight.grad
        # instead of scatter-adding a dense (V, D) — the reference's
        # row_sparse embedding-gradient path (``Embedding(sparse_grad=
        # True)`` + sparse optimizer updates touching live rows only).
        # Under hybridize()/TrainStep tracing the dense XLA scatter path
        # is used (jit gradients are whole-program).
        self._sparse_grad = bool(sparse_grad)
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim),
                init=weight_initializer, dtype=dtype,
                grad_stype="row_sparse" if sparse_grad else "default",
            )

    def hybrid_forward(self, F, x, weight):
        if self._sparse_grad:
            from ... import autograd as _ag
            from ...gluon.block import _in_trace

            if _ag.is_recording() and not _in_trace():
                return _sparse_embedding_apply(
                    x, self.weight, self._input_dim, self._output_dim
                )
        return F.Embedding(
            x, weight, input_dim=self._input_dim, output_dim=self._output_dim
        )

    def __repr__(self):
        return f"Embedding({self._input_dim} -> {self._output_dim})"


class BatchNorm(HybridBlock):
    """Batch normalization with moving-average aux states.

    Reference: Gluon ``BatchNorm`` over ``src/operator/nn/batch_norm.cc``
    [unverified]. The op here is pure (returns batch mean/var); this layer
    applies the moving-average update — through the CachedOp aux sink when
    staged, in place when eager.
    """

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {
            "axis": axis, "eps": epsilon, "momentum": momentum,
            "fix_gamma": not scale, "use_global_stats": use_global_stats,
        }
        self._axis = axis
        self._momentum = momentum
        self._use_global_stats = use_global_stats
        self._in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale,
            )
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center,
            )
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False,
            )
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False,
            )

    def infer_shape(self, x, *args):
        channels = int(x.shape[self._axis])
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (channels,)

    def cast(self, dtype):
        if _np.dtype(dtype).name in ("float16", "bfloat16"):
            dtype = "float32"  # keep BN stats in fp32 (AMP-safe, like ref)
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        training = autograd.is_training() and not self._use_global_stats
        out, mean, var = F.BatchNorm(
            x, gamma, beta, running_mean, running_var,
            training=training, **self._kwargs,
        )
        if training:
            with autograd.pause():
                m = self._momentum
                self.running_mean._aux_update(
                    m * running_mean.data + (1 - m) * mean.data
                )
                self.running_var._aux_update(
                    m * running_var.data + (1 - m) * var.data
                )
        return out

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return f"BatchNorm(axis={self._axis}, eps={self._kwargs['eps']}, " \
               f"momentum={self._momentum}, in_channels={in_channels})"


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        self._in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
            )
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
            )

    def infer_shape(self, x, *args):
        channels = int(x.shape[self._axis])
        self.gamma.shape = (channels,)
        self.beta.shape = (channels,)

    def hybrid_forward(self, F, x, gamma, beta):
        if self._axis == 1:
            return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)
        x = x.swapaxes(1, self._axis)
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon).swapaxes(
            1, self._axis
        )


class LayerNorm(HybridBlock):
    """Layer normalization (reference: ``src/operator/nn/layer_norm.cc``)."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        self._in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
            )
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
            )

    def infer_shape(self, x, *args):
        channels = int(x.shape[self._axis])
        self.gamma.shape = (channels,)
        self.beta.shape = (channels,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)

    def __repr__(self):
        return f"LayerNorm(axis={self._axis}, eps={self._epsilon})"


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._num_groups = num_groups
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
            )
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
            )

    def infer_shape(self, x, *args):
        channels = int(x.shape[1])
        self.gamma.shape = (channels,)
        self.beta.shape = (channels,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(
            x, gamma, beta, num_groups=self._num_groups, eps=self._epsilon
        )


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return "Flatten"


class Lambda(Block):
    """Wrap a function (name of an nd op or a callable) as a Block."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd

            if not hasattr(nd, function):
                raise MXNetError(f"function {function} not found in nd namespace")
            self._func_impl = getattr(nd, function)
        elif callable(function):
            self._func_impl = function
        else:
            raise MXNetError("function must be a str op name or a callable")
        self._func_name = getattr(self._func_impl, "__name__", "custom")

    def forward(self, *args):
        return self._func_impl(*args)

    def __repr__(self):
        return f"Lambda({self._func_name})"


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd

            if not hasattr(nd, function):
                raise MXNetError(f"function {function} not found in nd namespace")
            fname = function
            self._func = lambda F, *args: getattr(F, fname)(*args)
        elif callable(function):
            self._func = function
        else:
            raise MXNetError("function must be a str op name or a callable")
        self._func_name = getattr(function, "__name__", str(function))

    def hybrid_forward(self, F, x, *args):
        return self._func(F, x, *args)

    def __repr__(self):
        return f"HybridLambda({self._func_name})"
