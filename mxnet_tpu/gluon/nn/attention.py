"""Attention layers over the Pallas flash kernel.

API parity target: the reference's interleaved multi-head attention ops
(``src/operator/contrib/transformer.cc`` [unverified], used by GluonNLP
BERT) — one fused QKV projection, heads split internally. The score matrix
is never materialized (flash path), so long sequences are O(S) memory:
beyond-reference capability per SURVEY.md §5.
"""

from __future__ import annotations

import math

from ...base import MXNetError
from ..block import HybridBlock
from .basic_layers import Dense, Dropout

__all__ = ["MultiHeadAttention"]


class MultiHeadAttention(HybridBlock):
    """Fused multi-head attention.

    Parameters
    ----------
    units : total hidden size (= num_heads * head_dim)
    num_heads : number of attention heads
    dropout : attention output dropout rate
    use_bias : bias on projections
    self_attention : if True one fused QKV projection (interleaved layout,
        matching ``_contrib_interleaved_matmul_selfatt_*`` semantics)
    causal : apply causal mask (decoder self-attention)
    """

    def __init__(self, units, num_heads, dropout=0.0, use_bias=True,
                 self_attention=True, causal=False, flatten=False,
                 ring_axis=None, seq_mode="ring", **kwargs):
        super().__init__(**kwargs)
        if units % num_heads != 0:
            raise MXNetError(
                f"units {units} not divisible by num_heads {num_heads}"
            )
        if seq_mode not in ("ring", "ulysses"):
            raise MXNetError(f"unknown seq_mode {seq_mode!r}")
        self._units = units
        self._num_heads = num_heads
        self._head_dim = units // num_heads
        self._causal = causal
        self._self_attention = self_attention
        # sequence/context parallelism: name of the mesh axis the sequence
        # dim is sharded over; resolved against parallel.current_mesh() at
        # forward time. seq_mode picks the collective pattern: 'ring'
        # (K/V ppermute rotation) or 'ulysses' (head<->seq all_to_all,
        # needs num_heads % axis_size == 0)
        self._ring_axis = ring_axis
        self._seq_mode = seq_mode
        with self.name_scope():
            if self_attention:
                self.qkv_proj = Dense(3 * units, use_bias=use_bias,
                                      flatten=False, prefix="qkv_")
            else:
                self.q_proj = Dense(units, use_bias=use_bias, flatten=False,
                                    prefix="q_")
                self.k_proj = Dense(units, use_bias=use_bias, flatten=False,
                                    prefix="k_")
                self.v_proj = Dense(units, use_bias=use_bias, flatten=False,
                                    prefix="v_")
            self.out_proj = Dense(units, use_bias=use_bias, flatten=False,
                                  prefix="out_")
            self.drop = Dropout(dropout) if dropout else None

    def _split(self, x):
        # (B, S, units) -> (B, H, S, head_dim)
        B, S = x.shape[0], x.shape[1]
        return x.reshape(B, S, self._num_heads, self._head_dim).transpose(
            0, 2, 1, 3
        )

    def _merge(self, x):
        B, H, S, D = x.shape
        return x.transpose(0, 2, 1, 3).reshape(B, S, H * D)

    def hybrid_forward(self, F, query, key=None, value=None,
                       valid_length=None):
        """``valid_length`` (B,) int: number of non-padding KEY positions per
        batch row (reference softmax ``use_length`` semantics); keys past it
        are masked out of the attention."""
        use_bshd = self._use_bshd()
        if self._self_attention:
            qkv = self.qkv_proj(query)  # (B, S, 3*units)
            B, S = qkv.shape[0], qkv.shape[1]
            qkv = qkv.reshape(B, S, self._num_heads, 3 * self._head_dim)
            if use_bshd:
                # transpose-free layout: slices stay (B, S, H, D) and the
                # bshd attention path consumes them directly (measured
                # perf-neutral on v5e — see traces/README round-4 copy
                # audit; kept for the simpler graphs)
                d = self._head_dim
                q = qkv[:, :, :, 0 * d:1 * d]
                k = qkv[:, :, :, 1 * d:2 * d]
                v = qkv[:, :, :, 2 * d:3 * d]
            else:
                q = self._split_packed(qkv, 0)
                k = self._split_packed(qkv, 1)
                v = self._split_packed(qkv, 2)
        else:
            if key is None:
                key = query
            if value is None:
                value = key
            if use_bshd:
                def _heads(x):
                    return x.reshape(x.shape[0], x.shape[1],
                                     self._num_heads, self._head_dim)

                q = _heads(self.q_proj(query))
                k = _heads(self.k_proj(key))
                v = _heads(self.v_proj(value))
            else:
                q = self._split(self.q_proj(query))
                k = self._split(self.k_proj(key))
                v = self._split(self.v_proj(value))
        use_ring = self._ring_axis is not None
        if use_ring:
            from ..block import _in_probe
            from ...parallel import current_mesh
            from ...parallel.ring_attention import ring_flash_attention

            mesh = current_mesh()
            if _in_probe() or mesh is None:
                # shape probe and plain (meshless) inference — e.g. eval
                # after sync_params on one device — run the numerically
                # identical dense kernel; ring needs no mesh to be correct
                use_ring = False
            elif self._ring_axis not in mesh.axis_names:
                raise MXNetError(
                    f"ring_axis={self._ring_axis!r} not in the active "
                    f"mesh's axes {mesh.axis_names}"
                )
        if use_ring:
            if self._seq_mode == "ulysses":
                from ...parallel.ulysses import ulysses_attention

                out = ulysses_attention(
                    q, k, v, mesh, self._ring_axis, causal=self._causal,
                    sm_scale=1.0 / math.sqrt(self._head_dim),
                    valid_length=valid_length,
                )
            else:
                out = ring_flash_attention(
                    q, k, v, mesh, self._ring_axis, causal=self._causal,
                    sm_scale=1.0 / math.sqrt(self._head_dim),
                    valid_length=valid_length,
                )
        else:
            out = F.flash_attention(
                q, k, v, valid_length, causal=self._causal,
                sm_scale=1.0 / math.sqrt(self._head_dim),
                layout="BSHD" if use_bshd else "BHSD",
            )
        if use_bshd:
            out = out.reshape(out.shape[0], out.shape[1], self._units)
        else:
            out = self._merge(out)
        out = self.out_proj(out)
        if self.drop is not None:
            out = self.drop(out)
        return out

    def _use_bshd(self) -> bool:
        """Transpose-free (B, S, H, D) attention layout — measured
        perf-neutral on v5e (traces/README round-4 copy audit), kept as
        default for the simpler graphs; ring/ulysses shard over explicit
        head-major arrays, so they keep BHSD. MXTPU_ATTN_BSHD=0 restores
        head-major."""
        import os

        return self._ring_axis is None and \
            os.environ.get("MXTPU_ATTN_BSHD", "1") != "0"

    def _split_packed(self, qkv, which):
        # qkv (B, S, H, 3*D) interleaved per head like the reference's
        # interleaved_matmul_selfatt layout
        d = self._head_dim
        part = qkv[:, :, :, which * d : (which + 1) * d]
        return part.transpose(0, 2, 1, 3)
