"""Attention layers over the Pallas flash kernel.

API parity target: the reference's interleaved multi-head attention ops
(``src/operator/contrib/transformer.cc`` [unverified], used by GluonNLP
BERT) — one fused QKV projection, heads split internally. The score matrix
is never materialized (flash path), so long sequences are O(S) memory:
beyond-reference capability per SURVEY.md §5.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from ...base import MXNetError
from ..block import HybridBlock
from .basic_layers import Dense, Dropout

__all__ = ["MultiHeadAttention"]


class MultiHeadAttention(HybridBlock):
    """Fused multi-head attention.

    Parameters
    ----------
    units : total hidden size (= num_heads * head_dim)
    num_heads : number of attention heads
    dropout : attention output dropout rate
    use_bias : bias on projections
    self_attention : if True one fused QKV projection (interleaved layout,
        matching ``_contrib_interleaved_matmul_selfatt_*`` semantics)
    causal : apply causal mask (decoder self-attention)
    """

    def __init__(self, units, num_heads, dropout=0.0, use_bias=True,
                 self_attention=True, causal=False, flatten=False,
                 ring_axis=None, seq_mode="ring", **kwargs):
        super().__init__(**kwargs)
        if units % num_heads != 0:
            raise MXNetError(
                f"units {units} not divisible by num_heads {num_heads}"
            )
        if seq_mode not in ("ring", "ulysses"):
            raise MXNetError(f"unknown seq_mode {seq_mode!r}")
        self._units = units
        self._num_heads = num_heads
        self._head_dim = units // num_heads
        self._causal = causal
        self._self_attention = self_attention
        # sequence/context parallelism: name of the mesh axis the sequence
        # dim is sharded over; resolved against parallel.current_mesh() at
        # forward time. seq_mode picks the collective pattern: 'ring'
        # (K/V ppermute rotation) or 'ulysses' (head<->seq all_to_all,
        # needs num_heads % axis_size == 0)
        self._ring_axis = ring_axis
        self._seq_mode = seq_mode
        with self.name_scope():
            if self_attention:
                self.qkv_proj = Dense(3 * units, use_bias=use_bias,
                                      flatten=False, prefix="qkv_")
            else:
                self.q_proj = Dense(units, use_bias=use_bias, flatten=False,
                                    prefix="q_")
                self.k_proj = Dense(units, use_bias=use_bias, flatten=False,
                                    prefix="k_")
                self.v_proj = Dense(units, use_bias=use_bias, flatten=False,
                                    prefix="v_")
            self.out_proj = Dense(units, use_bias=use_bias, flatten=False,
                                  prefix="out_")
            self.drop = Dropout(dropout) if dropout else None

    def _split(self, x):
        # (B, S, units) -> (B, H, S, head_dim)
        B, S = x.shape[0], x.shape[1]
        return x.reshape(B, S, self._num_heads, self._head_dim).transpose(
            0, 2, 1, 3
        )

    def _merge(self, x):
        B, H, S, D = x.shape
        return x.transpose(0, 2, 1, 3).reshape(B, S, H * D)

    def hybrid_forward(self, F, query, key=None, value=None,
                       valid_length=None, q_offset=None):
        """``valid_length`` (B,) int: number of non-padding KEY positions per
        batch row (reference softmax ``use_length`` semantics); keys past it
        are masked out of the attention. ``q_offset`` (scalar or (B,)):
        absolute position of query row 0 for the causal mask — the
        incremental-decode contract where a short (typically length-1)
        query attends over a longer cached key prefix."""
        use_bshd = self._use_bshd()
        if self._self_attention:
            qkv = self.qkv_proj(query)  # (B, S, 3*units)
            B, S = qkv.shape[0], qkv.shape[1]
            qkv = qkv.reshape(B, S, self._num_heads, 3 * self._head_dim)
            if use_bshd:
                # transpose-free layout: slices stay (B, S, H, D) and the
                # bshd attention path consumes them directly (measured
                # perf-neutral on v5e — see traces/README round-4 copy
                # audit; kept for the simpler graphs)
                d = self._head_dim
                q = qkv[:, :, :, 0 * d:1 * d]
                k = qkv[:, :, :, 1 * d:2 * d]
                v = qkv[:, :, :, 2 * d:3 * d]
            else:
                q = self._split_packed(qkv, 0)
                k = self._split_packed(qkv, 1)
                v = self._split_packed(qkv, 2)
        else:
            if key is None:
                key = query
            if value is None:
                value = key
            if use_bshd:
                def _heads(x):
                    return x.reshape(x.shape[0], x.shape[1],
                                     self._num_heads, self._head_dim)

                q = _heads(self.q_proj(query))
                k = _heads(self.k_proj(key))
                v = _heads(self.v_proj(value))
            else:
                q = self._split(self.q_proj(query))
                k = self._split(self.k_proj(key))
                v = self._split(self.v_proj(value))
        use_ring = self._ring_axis is not None
        if use_ring and q_offset is not None:
            raise MXNetError(
                "q_offset (incremental decode) is not supported under "
                "sequence-parallel attention; decode with ring_axis=None")
        if use_ring:
            from ..block import _in_probe
            from ...parallel import current_mesh
            from ...parallel.ring_attention import ring_flash_attention

            mesh = current_mesh()
            if _in_probe() or mesh is None:
                # shape probe and plain (meshless) inference — e.g. eval
                # after sync_params on one device — run the numerically
                # identical dense kernel; ring needs no mesh to be correct
                use_ring = False
            elif self._ring_axis not in mesh.axis_names:
                raise MXNetError(
                    f"ring_axis={self._ring_axis!r} not in the active "
                    f"mesh's axes {mesh.axis_names}"
                )
        if use_ring:
            if self._seq_mode == "ulysses":
                from ...parallel.ulysses import ulysses_attention

                out = ulysses_attention(
                    q, k, v, mesh, self._ring_axis, causal=self._causal,
                    sm_scale=1.0 / math.sqrt(self._head_dim),
                    valid_length=valid_length,
                )
            else:
                out = ring_flash_attention(
                    q, k, v, mesh, self._ring_axis, causal=self._causal,
                    sm_scale=1.0 / math.sqrt(self._head_dim),
                    valid_length=valid_length,
                )
        else:
            out = F.flash_attention(
                q, k, v, valid_length, causal=self._causal,
                sm_scale=1.0 / math.sqrt(self._head_dim),
                layout="BSHD" if use_bshd else "BHSD",
                q_offset=q_offset,
            )
        if use_bshd:
            out = out.reshape(out.shape[0], out.shape[1], self._units)
        else:
            out = self._merge(out)
        out = self.out_proj(out)
        if self.drop is not None:
            out = self.drop(out)
        return out

    def _use_bshd(self) -> bool:
        """Transpose-free (B, S, H, D) attention layout — measured
        perf-neutral on v5e (traces/README round-4 copy audit), kept as
        default for the simpler graphs; ring/ulysses shard over explicit
        head-major arrays, so they keep BHSD. MXTPU_ATTN_BSHD=0 restores
        head-major."""
        import os

        return self._ring_axis is None and \
            os.environ.get("MXTPU_ATTN_BSHD", "1") != "0"

    def _split_packed(self, qkv, which):
        # qkv (B, S, H, 3*D) interleaved per head like the reference's
        # interleaved_matmul_selfatt layout
        d = self._head_dim
        part = qkv[:, :, :, which * d : (which + 1) * d]
        return part.transpose(0, 2, 1, 3)

    # ----------------------------------------------------- incremental mode
    # KV-cached decode (Pope et al. 2022). The incremental API always uses
    # the transpose-free (B, S, H, D) head layout — caches are raw jax
    # arrays (pytree leaves of the decode state the engine threads through
    # lax.while_loop), activations stay NDArrays. Self-attention caches are
    # (max_len, B, H, D) slots written with lax.dynamic_update_slice;
    # cross-attention "caches" are the memory projections, computed once at
    # prefill and static afterwards.

    def _heads_bshd(self, x):
        # (B, L, units) -> (B, L, H, D)
        return x.reshape(x.shape[0], x.shape[1], self._num_heads,
                         self._head_dim)

    def _sm_scale(self):
        return 1.0 / math.sqrt(self._head_dim)

    def _finish(self, F, out):
        out = out.reshape(out.shape[0], out.shape[1], self._units)
        out = self.out_proj(out)
        if self.drop is not None:
            out = self.drop(out)
        return out

    def prefill(self, query, valid_length=None):
        """Full-prefix forward that ALSO returns the projected K/V.

        Self-attention only. Returns ``(out, k, v)`` with ``out`` matching
        ``__call__`` bit-for-bit (same projections, same dense/flash
        dispatch) and ``k``/``v`` raw ``(B, S, H, D)`` arrays ready to be
        seeded into a decode cache."""
        from ... import ndarray as F

        if not self._self_attention:
            raise MXNetError("prefill() is the self-attention cache seed; "
                             "cross-attention uses project_kv()")
        qkv = self.qkv_proj(query)
        B, S = qkv.shape[0], qkv.shape[1]
        qkv = qkv.reshape(B, S, self._num_heads, 3 * self._head_dim)
        d = self._head_dim
        q = qkv[:, :, :, 0 * d:1 * d]
        k = qkv[:, :, :, 1 * d:2 * d]
        v = qkv[:, :, :, 2 * d:3 * d]
        out = F.flash_attention(
            q, k, v, valid_length, causal=self._causal,
            sm_scale=self._sm_scale(), layout="BSHD")
        return self._finish(F, out), k.data, v.data

    def project_kv(self, key, value=None):
        """Cross-attention prefill: project the (static) memory once into
        raw ``(B, S, H, D)`` K/V reused by every decode step."""
        if self._self_attention:
            raise MXNetError("project_kv() needs self_attention=False")
        if value is None:
            value = key
        k = self._heads_bshd(self.k_proj(key))
        v = self._heads_bshd(self.v_proj(value))
        return k.data, v.data

    def attend(self, query, k, v, valid_length=None, q_offset=None):
        """Attention of a projected query over precomputed raw
        ``(B, S, H, D)`` K/V (from ``project_kv``) — the cross-attention
        half of both prefill and decode."""
        from ... import ndarray as F
        from ...ndarray.ndarray import NDArray

        if self._self_attention:
            raise MXNetError("attend() runs over external K/V; "
                             "self-attention caches use step()")
        q = self._heads_bshd(self.q_proj(query))
        out = F.flash_attention(
            q, NDArray(k), NDArray(v), valid_length, causal=self._causal,
            sm_scale=self._sm_scale(), layout="BSHD", q_offset=q_offset)
        return self._finish(F, out)

    def step(self, query, k_cache, v_cache, pos, valid_length=None):
        """One incremental self-attention step: O(1) work per token.

        ``query`` (B, 1, units) is the current token's hidden state;
        ``k_cache``/``v_cache`` are raw ``(max_len, B, H, D)`` slots
        holding ``pos`` earlier entries; ``pos`` is a (traced) scalar
        int32 cache offset. The new token's K/V land at row ``pos`` via
        ``lax.dynamic_update_slice`` and the query attends causally over
        the cache with ``q_offset=pos`` (the non-square mask fix).
        Returns ``(out, k_cache, v_cache)`` with the updated caches."""
        import jax
        from ... import ndarray as F
        from ...ndarray.ndarray import NDArray

        if not self._self_attention:
            raise MXNetError("step() updates a self-attention cache; "
                             "cross-attention uses attend()")
        qkv = self.qkv_proj(query)
        B = qkv.shape[0]
        qkv = qkv.reshape(B, 1, self._num_heads, 3 * self._head_dim)
        d = self._head_dim
        q = qkv[:, :, :, 0 * d:1 * d]
        k_t = qkv[:, :, :, 1 * d:2 * d].data
        v_t = qkv[:, :, :, 2 * d:3 * d].data
        idx = (pos.data if hasattr(pos, "asnumpy") else pos, 0, 0, 0)
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, jnp.swapaxes(k_t, 0, 1), idx)
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, jnp.swapaxes(v_t, 0, 1), idx)
        out = F.flash_attention(
            q, NDArray(jnp.swapaxes(k_cache, 0, 1)),
            NDArray(jnp.swapaxes(v_cache, 0, 1)),
            valid_length, causal=self._causal, sm_scale=self._sm_scale(),
            layout="BSHD", q_offset=idx[0])
        return self._finish(F, out), k_cache, v_cache

    def init_cache(self, batch_size, max_len, dtype=None):
        """Zeroed raw ``(max_len, B, H, D)`` K/V cache pair for ``step``.
        ``dtype`` defaults to the layer's parameter dtype (so AMP-cast
        engines allocate compute-dtype caches)."""
        if dtype is None:
            dtype = self.out_proj.weight.dtype
        shape = (int(max_len), int(batch_size), self._num_heads,
                 self._head_dim)
        z = jnp.zeros(shape, jnp.dtype(dtype))
        return z, z

    # ------------------------------------------------------------ paged mode
    # Paged KV cache (Kwon et al., PagedAttention, SOSP 2023): instead of a
    # dense (max_len, B, H, D) slab per dispatch, K/V live in a shared
    # (num_pages, page_size, H, D) pool; each batch row owns a PAGE TABLE
    # row mapping its logical token positions to pool pages. Reads gather
    # through the table, writes scatter through it — so a request holds
    # only ceil(len/page_size) pages, freed the moment it retires. Page 0
    # is reserved as the TRASH page: inactive/finished rows write there and
    # padded table entries point at it, keeping every dispatch shape-stable
    # with no masking branches. serving.pages.PagePool owns the free list.

    def init_page_pool(self, num_pages, page_size, dtype=None):
        """Zeroed ``(num_pages, page_size, H, D)`` K/V pool pair shared by
        every request decoding through this layer. ``dtype`` defaults to
        the layer's parameter dtype (AMP engines get compute-dtype pools).
        """
        if dtype is None:
            dtype = self.out_proj.weight.dtype
        shape = (int(num_pages), int(page_size), self._num_heads,
                 self._head_dim)
        z = jnp.zeros(shape, jnp.dtype(dtype))
        return z, z

    def paged_step(self, query, k_pool, v_pool, page_table, pos, active):
        """One incremental self-attention step through a paged KV cache.

        ``query`` (B, 1, units) is the current token's hidden state;
        ``k_pool``/``v_pool`` are the shared ``(num_pages, page_size, H,
        D)`` pools; ``page_table`` (B, P) int32 maps row ``b``'s logical
        position ``p`` to pool page ``page_table[b, p // page_size]``;
        ``pos`` (B,) int32 is each row's cache length (= this token's
        absolute position); ``active`` (B,) bool masks live rows — rows
        that finished (or hold no request) write to the trash page 0, so
        their garbage never lands in another request's pages.

        The new token's K/V scatter to ``(page, pos % page_size)``; then
        attention routes by ``paged_flash_attention.flash_paged_enabled()``:
        the Pallas decode kernel walks the page table inside its grid and
        reads the pools IN PLACE (no gather), while the fallback gathers
        the ``(B, P*page_size, H, D)`` view and runs the dense path —
        identical masked-softmax math to the dense ``step`` path, so at
        equal logical capacity the two are bit-identical (asserted in
        tests/test_paged.py). Either way inactive rows only ever touch
        trash page 0. Returns ``(out, k_pool, v_pool)``."""
        from ... import ndarray as F
        from ...ndarray.ndarray import NDArray

        if not self._self_attention:
            raise MXNetError("paged_step() updates a self-attention cache; "
                             "cross-attention uses attend()")
        qkv = self.qkv_proj(query)
        B = qkv.shape[0]
        qkv = qkv.reshape(B, 1, self._num_heads, 3 * self._head_dim)
        d = self._head_dim
        q = qkv[:, :, :, 0 * d:1 * d]
        k_t = qkv[:, :, :, 1 * d:2 * d].data[:, 0]  # (B, H, D)
        v_t = qkv[:, :, :, 2 * d:3 * d].data[:, 0]
        pos = jnp.asarray(pos, jnp.int32)
        page_size = k_pool.shape[1]
        rows = jnp.arange(B)
        # inactive rows resolve to (trash page, offset 0); pos // page_size
        # is in-bounds for active rows by the PagePool.ensure() contract
        slot = jnp.where(active, pos // page_size, 0)
        page = jnp.where(active, page_table[rows, slot], 0)
        off = jnp.where(active, pos % page_size, 0)
        k_pool = k_pool.at[page, off].set(k_t)
        v_pool = v_pool.at[page, off].set(v_t)
        from ...ops.pallas import paged_flash_attention as _pfa

        if self._causal and _pfa.flash_paged_enabled():
            # Pallas decode kernel: the page table rides the grid as a
            # scalar-prefetch operand and each step reads one pool page
            # in place — the gather below never materializes
            out = NDArray(_pfa.paged_decode_attention(
                q.data[:, 0], k_pool, v_pool, page_table, pos,
                sm_scale=self._sm_scale())[:, None])
        else:
            # dense fallback: gather the logical (B, P*page_size, H, D)
            # view through the table (bitwise the pre-kernel path)
            P = page_table.shape[1]
            k = k_pool[page_table].reshape(B, P * page_size,
                                           self._num_heads, d)
            v = v_pool[page_table].reshape(B, P * page_size,
                                           self._num_heads, d)
            out = F.flash_attention(
                q, NDArray(k), NDArray(v), None, causal=self._causal,
                sm_scale=self._sm_scale(), layout="BSHD", q_offset=pos)
        return self._finish(F, out), k_pool, v_pool

    def paged_window_step(self, query, k_pool, v_pool, page_table, pos,
                          active, window_vl=None):
        """An S-token incremental window through the paged cache in ONE
        pass — the q_offset-aware prefill shape that suffix-only prefix
        replay and speculative verification both dispatch.

        ``query`` (B, S, units): token ``i`` of row ``b`` sits at
        absolute position ``pos[b] + i``. The window's K/V scatter
        through the page table first (inactive rows to trash page 0),
        then every query attends causally over the row's full paged
        history INCLUDING the window's earlier tokens. ``window_vl``
        (B,) marks tokens ``>= window_vl[b]`` as padding: their K/V go
        to the trash page and their outputs are zeroed under the kernel
        path (garbage-but-ignored under the dense fallback — callers
        only read rows ``< window_vl``). Routing matches ``paged_step``:
        Pallas window kernel when ``flash_paged_enabled()``, dense
        gather otherwise. Returns ``(out, k_pool, v_pool)``."""
        from ... import ndarray as F
        from ...ndarray.ndarray import NDArray
        from ...ops.pallas import paged_flash_attention as _pfa

        if not self._self_attention:
            raise MXNetError("paged_window_step() updates a self-attention "
                             "cache; cross-attention uses attend()")
        qkv = self.qkv_proj(query)
        B, S = qkv.shape[0], qkv.shape[1]
        qkv = qkv.reshape(B, S, self._num_heads, 3 * self._head_dim)
        d = self._head_dim
        q = qkv[:, :, :, 0 * d:1 * d]
        k_t = qkv[:, :, :, 1 * d:2 * d].data  # (B, S, H, D)
        v_t = qkv[:, :, :, 2 * d:3 * d].data
        pos = jnp.asarray(pos, jnp.int32)
        page_size = k_pool.shape[1]
        steps = jnp.arange(S, dtype=jnp.int32)[None, :]
        abs_pos = pos[:, None] + steps                    # (B, S)
        live = active[:, None]
        if window_vl is not None:
            live = jnp.logical_and(
                live, steps < jnp.asarray(window_vl, jnp.int32)[:, None])
        rows = jnp.arange(B)[:, None]
        slot = jnp.where(live, abs_pos // page_size, 0)
        page = jnp.where(live, page_table[rows, slot], 0)
        off = jnp.where(live, abs_pos % page_size, 0)
        k_pool = k_pool.at[page, off].set(k_t)
        v_pool = v_pool.at[page, off].set(v_t)
        if self._causal and _pfa.flash_paged_enabled():
            out = NDArray(_pfa.paged_window_attention(
                q.data, k_pool, v_pool, page_table, pos, window_vl,
                sm_scale=self._sm_scale()))
        else:
            P = page_table.shape[1]
            k = k_pool[page_table].reshape(B, P * page_size,
                                           self._num_heads, d)
            v = v_pool[page_table].reshape(B, P * page_size,
                                           self._num_heads, d)
            out = F.flash_attention(
                q, NDArray(k), NDArray(v), None, causal=self._causal,
                sm_scale=self._sm_scale(), layout="BSHD", q_offset=pos)
        return self._finish(F, out), k_pool, v_pool
