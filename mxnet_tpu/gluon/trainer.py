"""Trainer: binds Parameters to an optimizer + KVStore (reference:
``python/mxnet/gluon/trainer.py`` [unverified]).

Reference flow (SURVEY.md §3.3): ``step()`` → allreduce grads via KVStore
push/pull → fused optimizer update per param. Here the single-process path
updates each param through a jitted fused-update op; multi-host grads are
psum'd through the dist KVStore facade; GSPMD data-parallel inside a jitted
step needs no Trainer-level sync at all (the collective is compiled in).
"""

from __future__ import annotations

import functools
import logging
import time as _time
from typing import Optional

import numpy as _np
import jax.numpy as jnp

from ..base import MXNetError
from .. import optimizer as opt
from .. import telemetry as _tel
from ..kvstore import KVStore as _KV
from ..ndarray.ndarray import NDArray
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


def _fused_jit_enabled() -> bool:
    import os

    return os.environ.get("MXTPU_EAGER_JIT", "1") != "0"


@functools.lru_cache(maxsize=64)
def _fused_sgd_fn(n: int, momentum: float, clip: float):
    import jax

    # the per-tensor math is the op library's (_apply_wd_rescale is the
    # single source of rescale/clip/wd ordering — shared with
    # sgd_update/multi_sgd_update so the three paths cannot diverge)
    from ..ops.optimizer_op import _apply_wd_rescale

    def apply(ws, gs, ms, lrs, wds, rescale):
        new_w, new_m = [], []
        for i in range(n):
            g = _apply_wd_rescale(ws[i], gs[i], wds[i], rescale,
                                  clip if clip >= 0 else None)
            if momentum:
                m = momentum * ms[i] - lrs[i] * g
                new_m.append(m)
                new_w.append(ws[i] + m)
            else:
                new_w.append(ws[i] - lrs[i] * g)
        return tuple(new_w), tuple(new_m) if momentum else None

    return jax.jit(apply)


@functools.lru_cache(maxsize=64)
def _fused_adam_fn(n: int, beta1: float, beta2: float, eps: float,
                   clip: float, decoupled_wd: bool, bias_corr: bool,
                   low_dtypes: tuple = ()):
    import jax

    # per-tensor math mirrors ops/optimizer_op.adam_update (coupled wd via
    # _apply_wd_rescale ordering) and adamw_update (decoupled, wd outside
    # the moments); bias correction folds into lr IN-GRAPH from the ts
    # vector — the same f32 formulation TrainStep compiles, so the three
    # Adam paths agree to f32 resolution.
    # low_dtypes: per-tensor low-precision weight dtype name ('' = plain
    # f32 weight). A named entry is the MULTI-PRECISION case: ws[i] is the
    # f32 MASTER, gs[i] arrives in the low dtype (upcast in-graph), and a
    # fresh low-precision weight is returned alongside the master — the
    # reference's mp_*_update contract, one fused launch for all params.
    from ..ops.optimizer_op import _apply_wd_rescale

    low_dtypes = low_dtypes or ("",) * n

    def apply(ws, gs, ms, vs, lrs, wds, ts, rescale):
        new_w, new_m, new_v, new_low = [], [], [], []
        for i in range(n):
            g32 = gs[i].astype(jnp.float32)
            if decoupled_wd:
                g = g32 * rescale
                if clip >= 0:
                    g = jnp.clip(g, -clip, clip)
            else:
                g = _apply_wd_rescale(ws[i], g32, wds[i], rescale,
                                      clip if clip >= 0 else None)
            lr = lrs[i]
            if bias_corr:
                lr = lr * jnp.sqrt(1.0 - beta2 ** ts[i]) / \
                    (1.0 - beta1 ** ts[i])
            m = beta1 * ms[i] + (1.0 - beta1) * g
            v = beta2 * vs[i] + (1.0 - beta2) * jnp.square(g)
            upd = m / (jnp.sqrt(v) + eps)
            if decoupled_wd:
                upd = upd + wds[i] * ws[i]
            w1 = ws[i] - lr * upd
            new_w.append(w1)
            new_m.append(m)
            new_v.append(v)
            new_low.append(w1.astype(jnp.dtype(low_dtypes[i]))
                           if low_dtypes[i] else None)
        return tuple(new_w), tuple(new_m), tuple(new_v), tuple(new_low)

    return jax.jit(apply)


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError(
                "first argument must be a list or dict of Parameters, "
                f"got {type(params)}"
            )
        self._params = []
        for param in params:
            if not isinstance(param, Parameter):
                raise MXNetError(
                    "first argument must be a list or dict of Parameters, "
                    f"got list of {type(param)}"
                )
            if param.grad_req != "null":
                self._params.append(param)
        # name -> index in the FILTERED list (the index space used for
        # optimizer state and kvstore keys)
        self._param2idx = {p.name: i for i, p in enumerate(self._params)}
        self._compression_params = compression_params
        self._contains_sparse_weight = False
        optimizer_params = optimizer_params if optimizer_params else {}
        self._init_optimizer(optimizer, optimizer_params)
        self._scale = self._optimizer.rescale_grad
        self._kvstore_params = {
            "kvstore": kvstore,
            "update_on_kvstore": update_on_kvstore,
        }
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._states_to_load = None
        self._grad_keys_inited = False

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, (
                "optimizer_params must be None if optimizer is an Optimizer "
                "instance"
            )
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(
                optimizer, param_dict=param_dict, **optimizer_params
            )
        self._updaters = [opt.get_updater(self._optimizer)]

    def _init_kvstore(self):
        config = self._kvstore_params
        kvstore = config["kvstore"]
        update_on_kvstore = config["update_on_kvstore"]
        if kvstore:
            kv = kvstore if isinstance(kvstore, _KV) else None
            if kv is None:
                from .. import kvstore as kvstore_mod

                kv = kvstore_mod.create(kvstore)
            self._kvstore = kv
            if self._compression_params:
                kv.set_gradient_compression(self._compression_params)
            if update_on_kvstore is None:
                update_on_kvstore = kv.num_workers > 1
            if update_on_kvstore:
                kv.set_optimizer(self._optimizer)
            for i, param in enumerate(self._params):
                kv.init(i, param.data())
        else:
            self._kvstore = None
            self._update_on_kvstore = False
        self._update_on_kvstore = bool(update_on_kvstore) if kvstore else False
        self._kv_initialized = True
        if self._states_to_load is not None:
            self.load_states(self._states_to_load)
            self._states_to_load = None

    @property
    def learning_rate(self):
        return self._optimizer.lr_scheduler(self._optimizer.num_update) \
            if self._optimizer.lr_scheduler is not None else self._optimizer.lr

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    # ---------------------------------------------------------------- steps
    def step(self, batch_size, ignore_stale_grad=False):
        """Rescale by 1/batch_size, sync grads, apply optimizer update.

        Disabled-telemetry overhead is the single ``_tel._ENABLED`` flag
        check — no span or metric objects exist on that path."""
        if not _tel._ENABLED:
            if not self._kv_initialized:
                self._init_kvstore()
            self._optimizer.rescale_grad = self._scale / batch_size
            self._allreduce_grads()
            self._update(ignore_stale_grad)
            return
        t0 = _time.perf_counter()
        with _tel.span("trainer.step", {"batch_size": int(batch_size)}):
            if not self._kv_initialized:
                self._init_kvstore()
            self._optimizer.rescale_grad = self._scale / batch_size
            with _tel.span("trainer.allreduce_grads"):
                self._allreduce_grads()
            with _tel.span("trainer.update"):
                self._update(ignore_stale_grad)
        _tel.record_step(int(batch_size), _time.perf_counter() - t0)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            raise MXNetError(
                "allreduce_grads() when parameters are updated on kvstore "
                "is not supported"
            )
        if _tel._ENABLED:
            with _tel.span("trainer.allreduce_grads"):
                self._allreduce_grads()
        else:
            self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None or self._kvstore.num_workers == 1:
            return  # grads already global: single replica or in-program psum
        from ..parallel import sharding as _shard

        if _shard.mesh_spans_processes():
            # the process-global mesh covers every worker: gradient sync
            # is IN-GRAPH (GSPMD psum over the mesh) — the host-side
            # push/pull loop would double-sum on top of it. Count the
            # skip so the telemetry shows which sync path is live.
            if not getattr(self, "_mesh_sync_noted", False):
                self._mesh_sync_noted = True
                logging.getLogger(__name__).info(
                    "global mesh spans all %d processes: host KVStore "
                    "allreduce skipped (gradient sync is in-graph)",
                    self._kvstore.num_workers)
            if _tel._ENABLED:
                _tel.registry().counter(
                    "shard/host_allreduce_skipped").inc()
            return
        if self._update_on_kvstore:
            # the push inside _update() both all-reduces and applies the
            # server-side optimizer; pre-reducing here would double-sum and
            # run the updater against the gradient buffers
            return
        if not self._grad_keys_inited:
            # register gradient keys ONCE — init is idempotent but still
            # cost a span + dict probe per param per step when issued
            # unconditionally from this hot loop
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    self._kvstore.init(f"g{i}", param.grad())
            self._grad_keys_inited = True
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                grad = param.grad()
                self._kvstore.push(f"g{i}", grad)
                self._kvstore.pull(f"g{i}", grad)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            raise MXNetError(
                "update() when parameters are updated on kvstore is not "
                "supported; call step() instead"
            )
        self._optimizer.rescale_grad = self._scale / batch_size
        if _tel._ENABLED:
            with _tel.span("trainer.update"):
                self._update(ignore_stale_grad)
        else:
            self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        updater = self._updaters[0]
        if self._update_on_kvstore:
            for i, param in enumerate(self._params):
                self._kvstore.push(i, param.grad())
                self._kvstore.pull(i, param.data())
            return
        if self._fused_sgd_update(updater):
            return
        if self._fused_adam_update(updater):
            return
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            updater(i, param.grad(), param.data())

    def _fused_sgd_update(self, updater) -> bool:
        """Multi-tensor apply (reference ``multi_sgd_(mom_)update``,
        ``src/operator/optimizer_op.cc`` [unverified]): ONE jitted call
        updates every parameter — the whole optimizer step is a single
        dispatch instead of one per param, the same launch-amortization
        the reference's multi-tensor CUDA kernels bought. lr/wd arrive as
        device vectors so lr-schedule changes never retrigger a compile.

        Engages only for the plain dense f32 SGD(+momentum) case with
        the exact SGD class; anything else falls back to per-param
        updates."""
        opt_ = self._optimizer
        if type(opt_) is not opt.SGD or not _fused_jit_enabled():
            return False
        idxs, ws, gs, ms = [], [], [], []
        from ..ndarray.sparse import RowSparseNDArray

        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            w, g = param.data(), param.grad()
            if isinstance(g, RowSparseNDArray) or w.dtype != _np.float32:
                return False
            if i not in updater.states:
                updater.states[i] = opt_.create_state_multi_precision(i, w)
                updater.states_synced[i] = True
            st = updater.states[i]
            if st is not None and not isinstance(st, NDArray):
                return False  # multi-precision tuple state: fallback
            if (st is None) != (opt_.momentum == 0.0):
                return False
            idxs.append(i)
            ws.append(w)
            gs.append(g)
            ms.append(st)
        if not idxs:
            return False
        for i in idxs:
            opt_._update_count(i)
        # lr/wd/rescale are usually step-invariant: reuse their device
        # buffers (each jnp.asarray here is otherwise a full ~0.5ms
        # eager launch per step on the tunneled backend)
        host = ([opt_._get_lr(i) for i in idxs],
                [opt_._get_wd(i) for i in idxs], opt_.rescale_grad)
        memo = getattr(self, "_hyper_memo", None)
        if memo is None or memo[0] != host:
            self._hyper_memo = memo = (
                host, jnp.asarray(host[0], jnp.float32),
                jnp.asarray(host[1], jnp.float32), jnp.float32(host[2]))
        _, lrs, wds, rescale = memo
        clip = opt_.clip_gradient if opt_.clip_gradient is not None else -1.0
        fn = _fused_sgd_fn(len(idxs), float(opt_.momentum), float(clip))
        if opt_.momentum:
            new_w, new_m = fn(
                tuple(w.data for w in ws), tuple(g.data for g in gs),
                tuple(m.data for m in ms), lrs, wds, rescale)
            for w, m, nw, nm in zip(ws, ms, new_w, new_m):
                w._rebind(nw)
                m._rebind(nm)
        else:
            new_w, _ = fn(
                tuple(w.data for w in ws), tuple(g.data for g in gs),
                None, lrs, wds, rescale)
            for w, nw in zip(ws, new_w):
                w._rebind(nw)
        return True

    def _fused_adam_update(self, updater) -> bool:
        """Multi-tensor Adam/AdamW apply, ``_fused_sgd_update``'s shape for
        the adaptive optimizers: ONE jitted call updates every dense f32
        parameter and both moment states — a single dispatch per step
        instead of one per param. lr/wd ride memoized device vectors; the
        per-param step counts (``ts``, for bias correction) change every
        step and arrive as one small f32 vector.

        Engages for the exact Adam/AdamW classes over dense params with
        plain f32 ``(mean, var)`` states AND the multi-precision layout
        (``((mean, var), fp32 master)`` over a low-precision weight,
        from ``multi_precision=True``): the update runs on the f32
        master with the gradient upcast in-graph and the low-precision
        weight refreshed from the new master inside the SAME fused
        launch — the reference's ``mp_adamw_update`` contract. Sparse
        grads or any other state layout fall back to per-param
        updates."""
        opt_ = self._optimizer
        if type(opt_) not in (opt.Adam, opt.AdamW) or not _fused_jit_enabled():
            return False
        from ..ndarray.sparse import RowSparseNDArray

        idxs, ws, gs, ms, vs = [], [], [], [], []
        low_ws, low_dts = [], []
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            w, g = param.data(), param.grad()
            if isinstance(g, RowSparseNDArray):
                return False
            if i not in updater.states:
                updater.states[i] = opt_.create_state_multi_precision(i, w)
                updater.states_synced[i] = True
            st = updater.states[i]
            if (isinstance(st, tuple) and len(st) == 2
                    and isinstance(st[0], tuple)
                    and isinstance(st[1], NDArray)):
                # multi-precision: ((mean, var) on the master, master)
                inner, master = st
                if not (len(inner) == 2
                        and all(isinstance(s, NDArray) for s in inner)):
                    return False
                ws.append(master)
                low_ws.append(w)
                low_dts.append(w.data.dtype.name)
                ms.append(inner[0])
                vs.append(inner[1])
            elif (isinstance(st, tuple) and len(st) == 2
                    and all(isinstance(s, NDArray) for s in st)):
                if w.dtype != _np.float32:
                    return False  # low-precision w/o master: per-param path
                ws.append(w)
                low_ws.append(None)
                low_dts.append("")
                ms.append(st[0])
                vs.append(st[1])
            else:
                return False
            idxs.append(i)
            gs.append(g)
        if not idxs:
            return False
        for i in idxs:
            opt_._update_count(i)
        ts = tuple(float(opt_._index_update_count[i]) for i in idxs)
        host = ([opt_._get_lr(i) for i in idxs],
                [opt_._get_wd(i) for i in idxs], opt_.rescale_grad)
        memo = getattr(self, "_adam_hyper_memo", None)
        if memo is None or memo[0] != host:
            self._adam_hyper_memo = memo = (
                host, jnp.asarray(host[0], jnp.float32),
                jnp.asarray(host[1], jnp.float32), jnp.float32(host[2]))
        _, lrs, wds, rescale = memo
        clip = opt_.clip_gradient if opt_.clip_gradient is not None else -1.0
        decoupled = type(opt_) is opt.AdamW
        bias_corr = bool(opt_.correct_bias) if decoupled else True
        fn = _fused_adam_fn(len(idxs), float(opt_.beta1), float(opt_.beta2),
                            float(opt_.epsilon), float(clip), decoupled,
                            bias_corr, tuple(low_dts))
        new_w, new_m, new_v, new_low = fn(
            tuple(w.data for w in ws), tuple(g.data for g in gs),
            tuple(m.data for m in ms), tuple(v.data for v in vs),
            lrs, wds, jnp.asarray(ts, jnp.float32), rescale)
        for k, (w, m, v) in enumerate(zip(ws, ms, vs)):
            w._rebind(new_w[k])
            m._rebind(new_m[k])
            v._rebind(new_v[k])
            if low_ws[k] is not None:
                low_ws[k]._rebind(new_low[k])
        return True

    # ---------------------------------------------------------------- state
    def save_states(self, fname):
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        if not self._kv_initialized:
            self._states_to_load = fname
            return
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore.updater.optimizer
        else:
            with open(fname, "rb") as f:
                states = f.read()
            self._updaters[0].set_states(states)
            self._optimizer = self._updaters[0].optimizer
        self._optimizer.param_dict = {
            i: param for i, param in enumerate(self._params)
        }
