"""Gluon utilities (reference: ``python/mxnet/gluon/utils.py`` [unverified])."""

from __future__ import annotations

import hashlib
import os

import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..ndarray import array as nd_array

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split along batch axis into ``num_slice`` pieces (reference API)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}"
        )
    step = size // num_slice
    if not even_split and size < num_slice:
        step = 1
        num_slice = size
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split a batch across contexts.

    TPU note: with a single logical device list (the common case — GSPMD
    shards one array over the mesh instead of making per-device copies),
    this returns one piece per ctx exactly like the reference so existing
    training loops port unchanged."""
    if not isinstance(data, NDArray):
        data = nd_array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so the joint L2 norm <= max_norm (reference API)."""
    assert len(arrays) > 0
    total = jnp.sqrt(
        sum(jnp.sum(jnp.square(a.data.astype(jnp.float32))) for a in arrays)
    )
    total_f = float(total)
    if check_isfinite and not _np.isfinite(total_f):
        import warnings

        warnings.warn(
            "nan or inf is detected. Clipping results will be undefined.",
            stacklevel=2,
        )
    scale = max_norm / (total_f + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a._rebind(a.data * scale)
    return total_f


def check_sha1(filename, sha1_hash):
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    """Reference API. This build runs with zero egress: the file must already
    exist locally (path or MXNET_HOME cache); otherwise an error explains."""
    fname = url.split("/")[-1]
    if path is None:
        path = fname
    elif os.path.isdir(path):
        path = os.path.join(path, fname)
    if os.path.exists(path) and not overwrite and (
        sha1_hash is None or check_sha1(path, sha1_hash)
    ):
        return path
    raise MXNetError(
        f"cannot download {url}: this environment has no network egress. "
        f"Place the file at {path} manually."
    )
