"""Recurrent layers (reference: ``python/mxnet/gluon/rnn/`` [unverified]).

Placeholder module populated in a later milestone (fused RNN over lax.scan
plus cell-level API); importing it early keeps `gluon.rnn` importable."""

__all__ = []
