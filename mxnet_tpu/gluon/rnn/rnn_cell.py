"""RNN cells (reference: ``python/mxnet/gluon/rnn/rnn_cell.py``
[unverified]): step-level API with ``unroll``, sequential/bidirectional/
residual/dropout compositors. ``unroll`` is a Python loop — under
``hybridize()`` the whole unrolled graph stages into one XLA program."""

from __future__ import annotations

from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from ..block import HybridBlock
from ..nn import Dense  # noqa: F401 (reference parity import)

__all__ = [
    "RecurrentCell", "RNNCell", "LSTMCell", "GRUCell", "SequentialRNNCell",
    "BidirectionalCell", "DropoutCell", "ResidualCell", "ZoneoutCell",
]


class RecurrentCell(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):  # pragma: no cover - abstract
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        import jax.numpy as jnp

        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            shape = info["shape"]
            states.append(NDArray(jnp.zeros(shape)))
        return states

    def __call__(self, inputs, states=None, **kwargs):
        # cells take (input, states) per step (unlike Block.__call__ arity)
        self._counter += 1
        return super().__call__(inputs, states, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Run the cell over ``length`` steps (reference: ``unroll``)."""
        axis = layout.find("T")
        batch_axis = layout.find("N")
        batch_size = inputs.shape[batch_axis]
        if begin_state is None:
            begin_state = self.begin_state(batch_size)
        states = begin_state
        outputs = []
        for t in range(length):
            step_in = (
                inputs[t] if axis == 0 else inputs[:, t]
            )
            out, states = self(step_in, states)
            outputs.append(out)
        if merge_outputs is None or merge_outputs:
            from ...ndarray import stack

            outputs = stack(*outputs, axis=axis)
        if valid_length is not None:
            from ... import ndarray as nd

            outputs = nd.SequenceMask(
                outputs, sequence_length=valid_length, use_sequence_length=True,
                value=0, axis=axis,
            )
        return outputs, states


class _BaseFusableCell(RecurrentCell):
    """Single-step cell with i2h/h2h params (gates packed like the ref)."""

    def __init__(self, hidden_size, ngates, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        ng = ngates
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(ng * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(ng * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(ng * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(ng * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)
        self._ng = ng

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (
            self._ng * self._hidden_size, int(x.shape[-1])
        )


class RNNCell(_BaseFusableCell):
    def __init__(self, hidden_size, activation="tanh", input_size=0, **kwargs):
        super().__init__(hidden_size, 1, input_size, **kwargs)
        self._activation = activation

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = F.Activation(i2h + h2h, act_type=self._activation)
        return output, [output]


class LSTMCell(_BaseFusableCell):
    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, 4, input_size, **kwargs)

    def state_info(self, batch_size=0):
        return [
            {"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
            {"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
        ]

    def _alias(self):
        return "lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        H = self._hidden_size
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias, num_hidden=4 * H)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * H)
        gates = i2h + h2h
        slices = F.split(gates, num_outputs=4, axis=1)
        in_gate = F.sigmoid(slices[0])
        forget_gate = F.sigmoid(slices[1])
        in_transform = slices[2].tanh()
        out_gate = F.sigmoid(slices[3])
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * next_c.tanh()
        return next_h, [next_h, next_c]


class GRUCell(_BaseFusableCell):
    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(hidden_size, 3, input_size, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        H = self._hidden_size
        prev = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias, num_hidden=3 * H)
        h2h = F.FullyConnected(prev, h2h_weight, h2h_bias, num_hidden=3 * H)
        i2h_r, i2h_z, i2h_n = F.split(i2h, num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h_n = F.split(h2h, num_outputs=3, axis=1)
        reset = F.sigmoid(i2h_r + h2h_r)
        update = F.sigmoid(i2h_z + h2h_z)
        new_mem = (i2h_n + reset * h2h_n).tanh()
        next_h = (1.0 - update) * new_mem + update * prev
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack cells; state list is concatenated across children."""

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        info = []
        for cell in self._children.values():
            info.extend(cell.state_info(batch_size))
        return info

    def begin_state(self, batch_size=0, **kwargs):
        states = []
        for cell in self._children.values():
            states.extend(cell.begin_state(batch_size, **kwargs))
        return states

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def forward(self, inputs, states):
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            inputs, cell_states = cell(inputs, states[p : p + n])
            next_states.extend(cell_states)
            p += n
        return inputs, next_states


class DropoutCell(RecurrentCell):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def forward(self, inputs, states):
        from ... import ndarray as nd

        if self._rate > 0:
            inputs = nd.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class _ModifierCell(RecurrentCell):
    def __init__(self, base_cell):
        super().__init__(prefix=None, params=None)
        base_cell._modified = True
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size, **kwargs)
        self.base_cell._modified = True
        return begin


class ResidualCell(_ModifierCell):
    def forward(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states


class ZoneoutCell(_ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def reset(self):
        super().reset()
        self._prev_output = None

    def forward(self, inputs, states):
        from ... import autograd, ndarray as nd

        next_output, next_states = self.base_cell(inputs, states)
        if not autograd.is_training():
            return next_output, next_states

        def mask(p, like):
            return nd.Dropout(nd.ones_like(like), p=p, training=True) * (1 - p)

        prev = self._prev_output
        if prev is None:
            prev = nd.zeros_like(next_output)
        if self.zoneout_outputs > 0:
            m = mask(self.zoneout_outputs, next_output)
            next_output = m * next_output + (1 - m) * prev
        if self.zoneout_states > 0:
            masked = []
            for ns, os in zip(next_states, states):
                m = mask(self.zoneout_states, ns)
                masked.append(m * ns + (1 - m) * os)
            next_states = masked
        self._prev_output = next_output
        return next_output, next_states


class BidirectionalCell(RecurrentCell):
    def __init__(self, l_cell, r_cell, **kwargs):
        super().__init__(**kwargs)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")

    def state_info(self, batch_size=0):
        l, r = self._children["l_cell"], self._children["r_cell"]
        return l.state_info(batch_size) + r.state_info(batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        l, r = self._children["l_cell"], self._children["r_cell"]
        return l.begin_state(batch_size, **kwargs) + r.begin_state(
            batch_size, **kwargs
        )

    def __call__(self, inputs, states=None):
        raise MXNetError("BidirectionalCell cannot be stepped; use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ...ndarray import concat, stack

        axis = layout.find("T")
        batch_size = inputs.shape[layout.find("N")]
        if begin_state is None:
            begin_state = self.begin_state(batch_size)
        l_cell = self._children["l_cell"]
        r_cell = self._children["r_cell"]
        nl = len(l_cell.state_info())
        l_out, l_states = l_cell.unroll(
            length, inputs, begin_state[:nl], layout, True,
            valid_length=valid_length,
        )
        rev = inputs.flip(axis=axis) if hasattr(inputs, "flip") else inputs
        from ... import ndarray as nd

        rev = nd.flip(inputs, axis=axis)
        r_out, r_states = r_cell.unroll(
            length, rev, begin_state[nl:], layout, True,
            valid_length=valid_length,
        )
        r_out = nd.flip(r_out, axis=axis)
        outputs = nd.concat(l_out, r_out, dim=2)
        return outputs, l_states + r_states
