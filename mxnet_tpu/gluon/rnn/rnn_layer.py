"""Fused RNN layers (reference: ``python/mxnet/gluon/rnn/rnn_layer.py`` over
``src/operator/rnn.cc`` [unverified]). Parameters are registered per
layer/direction with the reference's names (``l0_i2h_weight``,
``r0_h2h_bias``, …) so checkpoints map; the forward packs them and calls the
fused ``RNN`` op (one ``lax.scan`` program on device)."""

from __future__ import annotations

import jax.numpy as jnp

from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, mode, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), f"invalid layout {layout}"
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        with self.name_scope():
            for i in range(num_layers):
                for j in ["l", "r"][: self._dir]:
                    self._register_param(
                        f"{j}{i}_i2h_weight",
                        (ng * nh, ni if i == 0 else nh * self._dir),
                        i2h_weight_initializer,
                    )
                    self._register_param(
                        f"{j}{i}_h2h_weight", (ng * nh, nh),
                        h2h_weight_initializer,
                    )
                    self._register_param(
                        f"{j}{i}_i2h_bias", (ng * nh,), i2h_bias_initializer
                    )
                    self._register_param(
                        f"{j}{i}_h2h_bias", (ng * nh,), h2h_bias_initializer
                    )

    def _register_param(self, name, shape, init):
        p = self.params.get(
            name, shape=shape, init=init, allow_deferred_init=True
        )
        self._reg_params[name] = p
        object.__setattr__(self, name, p)

    def __repr__(self):
        return (
            f"{self.__class__.__name__}({self._input_size} -> "
            f"{self._hidden_size}, {self._layout}, layers={self._num_layers}"
            f"{', bidirectional' if self._dir == 2 else ''})"
        )

    def state_info(self, batch_size=0):  # pragma: no cover - reference API
        raise NotImplementedError

    def infer_shape(self, x, *args):
        ni = int(x.shape[2] if self._layout == "TNC" else x.shape[2])
        for i in range(self._num_layers):
            for j in ["l", "r"][: self._dir]:
                p = self._reg_params[f"{j}{i}_i2h_weight"]
                p.shape = (
                    self._gates * self._hidden_size,
                    ni if i == 0 else self._hidden_size * self._dir,
                )

    def begin_state(self, batch_size=0, func=None, **kwargs):
        states = []
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        n_states = 2 if self._mode == "lstm" else 1
        for _ in range(n_states):
            states.append(NDArray(jnp.zeros(shape)))
        return states

    def hybrid_forward(self, F, inputs, states=None, **params):
        if self._layout == "NTC":
            inputs = inputs.swapaxes(0, 1)
        T, N = inputs.shape[0], inputs.shape[1]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(N)
        if isinstance(states, NDArray):
            states = [states]
        packed = self._pack_params(params)
        h0 = states[0]
        c0 = states[1] if self._mode == "lstm" and len(states) > 1 else None
        from ... import autograd

        result = F.RNN(
            inputs, packed, h0, c0,
            state_size=self._hidden_size, num_layers=self._num_layers,
            bidirectional=self._dir == 2, mode=self._mode, p=self._dropout,
            state_outputs=True, training=autograd.is_training(),
        )
        if self._mode == "lstm":
            outputs, hT, cT = result
            out_states = [hT, cT]
        else:
            outputs, hT = result
            out_states = [hT]
        if self._layout == "NTC":
            outputs = outputs.swapaxes(0, 1)
        return outputs if skip_states else (outputs, out_states)

    def _pack_params(self, params):
        """Flatten per-layer params into the fused op's packed layout
        (weights for every layer/direction first, then biases)."""
        order = []
        for i in range(self._num_layers):
            for j in ["l", "r"][: self._dir]:
                order.append(f"{j}{i}_i2h_weight")
                order.append(f"{j}{i}_h2h_weight")
        border = []
        for i in range(self._num_layers):
            for j in ["l", "r"][: self._dir]:
                border.append(f"{j}{i}_i2h_bias")
                border.append(f"{j}{i}_h2h_bias")
        flat = [params[n].reshape(-1) for n in order + border]
        from ...ndarray import concatenate

        return concatenate(flat, axis=0)


class RNN(_RNNLayer):
    """Elman RNN (relu/tanh) (reference API)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False, input_size=0,
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "rnn_" + activation,
                         **kwargs)


class LSTM(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "lstm", **kwargs)


class GRU(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "gru", **kwargs)
