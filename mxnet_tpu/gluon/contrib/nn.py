"""Contrib layers (reference: ``python/mxnet/gluon/contrib/nn/basic_layers.py``
[unverified]): structural composition blocks used by model zoos.
"""

from __future__ import annotations

from ..block import HybridBlock
from ..nn import HybridSequential

__all__ = ["Concurrent", "HybridConcurrent", "Identity"]


class HybridConcurrent(HybridSequential):
    """Runs children on the same input and concatenates outputs on ``axis``
    (reference: Inception-style branch merge)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        out = [child(x) for child in self]
        return F.concat(*out, dim=self.axis)


class Concurrent(HybridConcurrent):
    """Imperative alias (reference keeps both names)."""


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return x
