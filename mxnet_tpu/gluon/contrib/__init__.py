"""Gluon contrib (reference: ``python/mxnet/gluon/contrib/`` [unverified]).

Populated in a later milestone (estimator loop, contrib layers)."""

__all__ = []
