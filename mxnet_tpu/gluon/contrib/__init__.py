"""Gluon contrib (reference: ``python/mxnet/gluon/contrib/`` [unverified]):
the estimator training facade and structural contrib layers."""

from . import nn
from . import estimator
from .estimator import Estimator

__all__ = ["nn", "estimator", "Estimator"]
