"""Gluon Estimator: high-level fit loop with event handlers.

Reference: ``python/mxnet/gluon/contrib/estimator/`` [unverified] —
``Estimator.fit`` drives train/val epochs and dispatches lifecycle events
(TrainBegin/EpochBegin/BatchBegin/BatchEnd/EpochEnd/TrainEnd) to handler
objects. The TPU build keeps the same handler contracts; the training step
itself runs through the standard autograd + Trainer path (hybridize the net
for the staged XLA step).
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional, Sequence

from ... import autograd, metric as _metric
from ... import telemetry as _tel
from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from ..trainer import Trainer

__all__ = [
    "Estimator", "TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd",
    "BatchBegin", "BatchEnd", "StoppingHandler", "MetricHandler",
    "ValidationHandler", "LoggingHandler", "CheckpointHandler",
    "EarlyStoppingHandler",
]


# ------------------------------------------------------------ event mixins
class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        pass


# -------------------------------------------------------- builtin handlers
class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop on max_epoch / max_batch (reference default handler)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch and self.current_batch >= self.max_batch:
            self.stop_training = True

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch >= self.max_epoch:
            self.stop_training = True


class MetricHandler(EpochBegin, BatchEnd):
    """Reset metrics at epoch begin, update at batch end."""

    def __init__(self, metrics):
        self.metrics = _as_list(metrics)

    def epoch_begin(self, estimator, *args, **kwargs):
        for m in self.metrics:
            m.reset()

    def batch_end(self, estimator, *args, **kwargs):
        pred = kwargs.get("pred")
        label = kwargs.get("label")
        loss = kwargs.get("loss")
        for m in self.metrics:
            if isinstance(m, _metric.Loss):
                m.update(0, loss)
            else:
                m.update(label, pred)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Run validation every ``epoch_period`` epochs (or batch_period)."""

    def __init__(self, val_data, eval_fn, epoch_period=1, batch_period=None,
                 priority=-1000):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.current_batch = 0
        self.current_epoch = 0
        self.priority = priority

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self.eval_fn(self.val_data)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self.eval_fn(self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchEnd):
    """Periodic speed/metric logging (reference LOG_PER_EPOCH/LOG_PER_BATCH)."""

    LOG_PER_EPOCH = 1
    LOG_PER_BATCH = 2

    def __init__(self, log_interval="epoch", metrics=None):
        self.metrics = _as_list(metrics) if metrics else []
        if log_interval == "epoch":
            self.log_interval = self.LOG_PER_EPOCH
        else:
            self.log_interval = int(log_interval)
        self.batch_index = 0
        self.current_epoch = 0
        self._logger = logging.getLogger(__name__)
        self.processed_samples = 0
        self.last_tic = 0.0

    def train_begin(self, estimator, *args, **kwargs):
        self.last_tic = time.time()
        self._logger.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        self._logger.info("Training end: %d epochs", self.current_epoch)

    def epoch_begin(self, estimator, *args, **kwargs):
        self.batch_index = 0
        self.processed_samples = 0
        self.last_tic = time.time()

    def batch_end(self, estimator, *args, **kwargs):
        self.batch_index += 1
        batch = kwargs.get("batch")
        if batch is not None:
            self.processed_samples += _batch_size(batch)
        if self.log_interval != self.LOG_PER_EPOCH and \
                self.batch_index % self.log_interval == 0:
            self._log("Batch[%d]" % self.batch_index)

    def epoch_end(self, estimator, *args, **kwargs):
        if self.log_interval == self.LOG_PER_EPOCH:
            self._log("Epoch[%d]" % self.current_epoch)
        self.current_epoch += 1

    def _log(self, head):
        elapsed = max(time.time() - self.last_tic, 1e-9)
        parts = [f"{head} speed={self.processed_samples / elapsed:.1f} samples/s"]
        for m in self.metrics:
            name, value = m.get()
            parts.append(f"{name}={value}")
        self._logger.info(" ".join(str(p) for p in parts))
        self.last_tic = time.time()


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    """Save params every ``epoch_period`` epochs via net.save_parameters."""

    def __init__(self, model_dir, model_prefix="model", epoch_period=1,
                 max_checkpoints=5):
        import os

        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.epoch_period = epoch_period
        self.max_checkpoints = max_checkpoints
        self.current_epoch = 0
        self.saved = []
        os.makedirs(model_dir, exist_ok=True)

    def epoch_end(self, estimator, *args, **kwargs):
        import os

        self.current_epoch += 1
        if self.current_epoch % self.epoch_period:
            return
        path = os.path.join(
            self.model_dir,
            f"{self.model_prefix}-epoch{self.current_epoch}.params",
        )
        estimator.net.save_parameters(path)
        self.saved.append(path)
        while len(self.saved) > self.max_checkpoints:
            old = self.saved.pop(0)
            if os.path.exists(old):
                os.remove(old)


class EarlyStoppingHandler(TrainBegin, EpochEnd, TrainEnd):
    """Stop when ``monitor`` stops improving (reference semantics: mode
    auto-resolves from the metric name — 'acc'/'f1' max, losses min)."""

    def __init__(self, monitor, min_delta=0, patience=0, mode="auto"):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        if mode == "auto":
            name = monitor.get()[0] if hasattr(monitor, "get") else str(monitor)
            mode = "max" if any(k in name.lower()
                                for k in ("acc", "f1", "score")) else "min"
        self.mode = mode
        self.best = None
        self.wait = 0
        self.stop_training = False
        self.stopped_epoch = None
        self.current_epoch = 0

    def _improved(self, value):
        if self.best is None:
            return True
        if self.mode == "max":
            return value > self.best + self.min_delta
        return value < self.best - self.min_delta

    def epoch_end(self, estimator, *args, **kwargs):
        value = self.monitor.get()[1]
        if self._improved(value):
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.stop_training = True
                self.stopped_epoch = self.current_epoch
        self.current_epoch += 1

    def train_end(self, estimator, *args, **kwargs):
        if self.stopped_epoch is not None:
            logging.getLogger(__name__).info(
                "Early stopping at epoch %d (best %s=%s)",
                self.stopped_epoch, self.monitor.get()[0], self.best,
            )


# ---------------------------------------------------------------- Estimator
class Estimator:
    """High-level training facade (reference: ``gluon.contrib.estimator``).

    >>> est = Estimator(net, loss, train_metrics=mx.metric.Accuracy(),
    ...                 trainer=trainer)
    >>> est.fit(train_data, val_data, epochs=2)
    """

    def __init__(self, net, loss, train_metrics=None, val_metrics=None,
                 trainer=None, context=None, evaluation_loss=None,
                 train_step=None):
        self.net = net
        self.loss = loss
        self.evaluation_loss = evaluation_loss or loss
        self.train_metrics = _as_list(train_metrics) if train_metrics else []
        self.val_metrics = _as_list(val_metrics) if val_metrics else \
            [type(m)() for m in self.train_metrics]
        self.train_loss_metric = _metric.Loss("train_loss")
        # train_step: a parallel.TrainStep over the SAME net — fit then
        # drives the fused sharded XLA step (forward+backward+collectives+
        # optimizer in ONE donated program, mesh/sharding rules included)
        # instead of the eager autograd+Trainer path. No Trainer is built
        # in that mode (the step owns the optimizer); per-batch pred/label
        # stay on device, so only Loss-type train metrics update.
        self.train_step = train_step
        if train_step is not None:
            self.trainer = trainer
        else:
            self.trainer = trainer or Trainer(
                net.collect_params(), "adam", {"learning_rate": 1e-3}
            )
        self.context = context
        self.stop_training = False

    # -------------------------------------------------------------- predict
    def predict(self, data, batch_fn=None, engine=None):
        """Inference pass: run the net in predict mode over ``data`` and
        return the list of per-batch outputs.

        ``data`` yields batches — bare arrays (fed as the single input)
        or tuples (fed positionally; pass ``batch_fn(batch) -> inputs
        tuple`` to strip labels from a training loader). ``engine``: an
        optional ``parallel.infer.InferStep`` over the same net — batches
        then run through its jitted, shape-guarded forward (warm it with
        the loader's signature menu for a compile-free pass) instead of
        the eager/hybridized path.

        ``engine`` may also be a serving BATCHER (anything with
        ``submit()`` — the ``serving.make_batcher`` default is the
        paged-KV ``ContinuousBatcher``; ``MXTPU_BATCHER=fixed`` falls
        back to ``DynamicBatcher``): each batch's rows are then submitted
        as individual generation requests through iteration-level
        scheduling and the per-batch output is a ``(tokens (B, max_new),
        lengths (B,))`` NDArray pair, trimmed/padded exactly like
        ``InferStep.decode_n``. Batches are ``src`` arrays or ``(src,
        valid_length)`` tuples in that mode."""
        if engine is not None and hasattr(engine, "submit"):
            return self._predict_generate(data, batch_fn, engine)
        runner = engine if engine is not None else self.net
        outs = []
        for batch in data:
            if batch_fn is not None:
                inputs = batch_fn(batch)
            elif isinstance(batch, (list, tuple)):
                inputs = batch
            else:
                inputs = (batch,)
            with (_tel.span("estimator.predict_batch") if _tel._ENABLED
                  else _tel.NULL_SPAN):
                outs.append(runner(*inputs))
        return outs

    def _predict_generate(self, data, batch_fn, batcher):
        """Generation pass through a serving batcher: rows fan out as
        requests (continuous batching keeps the decode batch full across
        batch boundaries), results gather back into per-batch
        ``(tokens, lengths)`` pairs."""
        import numpy as np

        from ...ndarray.ndarray import NDArray
        from ... import ndarray as _nd

        outs = []
        for batch in data:
            if batch_fn is not None:
                batch = batch_fn(batch)
            if isinstance(batch, (list, tuple)):
                src = batch[0]
                vl = batch[1] if len(batch) > 1 else None
            else:
                src, vl = batch, None
            src = src.asnumpy() if isinstance(src, NDArray) \
                else np.asarray(src)
            src = src.astype(np.int32)
            B, L = src.shape
            if vl is None:
                vl_np = np.full((B,), L, np.int32)
            else:
                vl_np = (vl.asnumpy() if isinstance(vl, NDArray)
                         else np.asarray(vl)).astype(np.int32)
            with (_tel.span("estimator.predict_batch") if _tel._ENABLED
                  else _tel.NULL_SPAN):
                futs = [batcher.submit(
                    src[i, :vl_np[i]] if vl_np[i] else src[i, :1])
                    for i in range(B)]
                toks = np.full((B, batcher.max_new), batcher._pad,
                               np.int32)
                lengths = np.zeros((B,), np.int32)
                for i, f in enumerate(futs):
                    got = f.result(timeout=600)
                    n = min(len(got), batcher.max_new)
                    toks[i, :n] = got[:n]
                    lengths[i] = n
            outs.append((_nd.array(toks, dtype="int32"),
                         _nd.array(lengths, dtype="int32")))
        return outs

    # ------------------------------------------------------------- evaluate
    def evaluate(self, val_data):
        for m in self.val_metrics:
            m.reset()
        val_loss = _metric.Loss("val_loss")
        for batch in val_data:
            data, label = _split_batch(batch)
            pred = self.net(data)
            L = self.evaluation_loss(pred, label)
            val_loss.update(0, L)
            for m in self.val_metrics:
                m.update(label, pred)
        return [val_loss] + list(self.val_metrics)

    # ------------------------------------------------------------------ fit
    def fit(self, train_data, val_data=None, epochs=None,
            event_handlers=None, batches=None, batch_size=None,
            prefetch=None, warmup=False):
        """Drive training epochs. ``prefetch=N`` (or ``True``) is the
        opt-in async device feed: each epoch's batches are pulled and
        device_put by a background thread holding up to N staged batches
        (``gluon.data.prefetch.prefetch_to_device``), so the next batch's
        host->device transfer overlaps the current step.

        ``warmup=True`` compiles every batch-shape signature BEFORE the
        timed epochs: the loader is pre-scanned (bounded by
        ``MXTPU_WARMUP_SCAN`` batches) and one forward/backward runs per
        previously-unseen ``(data, label)`` shape, so a bucketed loader
        (``gluon.data.bucketing``) enters epoch 0 with all of its
        programs compiled and zero steady-state recompiles. Pass an
        iterable of ``((data_shape, dtype), (label_shape, dtype))`` pairs
        instead to warm explicit signatures on zero batches (note: aux
        state such as BatchNorm running stats sees the warmup passes)."""
        if epochs is None and batches is None:
            raise MXNetError("fit needs epochs or batches")
        handlers = self._prepare_handlers(event_handlers, val_data, epochs,
                                          batches)
        self.stop_training = False
        if warmup:
            self._warmup(train_data, warmup)

        _dispatch(handlers, "train_begin", self)
        epoch = 0
        while not self.stop_training:
            with (_tel.span("estimator.epoch", {"epoch": epoch})
                  if _tel._ENABLED else _tel.NULL_SPAN):
                _dispatch(handlers, "epoch_begin", self)
                self.train_loss_metric.reset()
                epoch_iter = self._epoch_iter(
                    train_data, prefetch, feed=self.train_step)
                try:
                    for batch in epoch_iter:
                        _dispatch(handlers, "batch_begin", self, batch=batch)
                        if self.train_step is not None:
                            pred = label = None
                            L = self._fused_step(batch)
                        elif _tel._ENABLED:
                            data, label = _split_batch(batch)
                            with _tel.span("estimator.forward_backward"):
                                with autograd.record():
                                    pred = self.net(data)
                                    L = self.loss(pred, label)
                                L.backward()
                            self.trainer.step(_batch_size(batch))
                        else:
                            data, label = _split_batch(batch)
                            with autograd.record():
                                pred = self.net(data)
                                L = self.loss(pred, label)
                            L.backward()
                            self.trainer.step(_batch_size(batch))
                        self.train_loss_metric.update(0, L)
                        _dispatch(handlers, "batch_end", self, batch=batch,
                                  pred=pred, label=label, loss=L)
                        self.stop_training = self.stop_training or any(
                            getattr(h, "stop_training", False)
                            for h in handlers
                        )
                        if self.stop_training:
                            break
                finally:
                    # an abandoned prefetch iterator must retire its
                    # staging thread (early stop / handler exception)
                    if epoch_iter is not train_data and \
                            hasattr(epoch_iter, "close"):
                        epoch_iter.close()
                _dispatch(handlers, "epoch_end", self)
            epoch += 1
            self.stop_training = self.stop_training or any(
                getattr(h, "stop_training", False) for h in handlers
            )
            if hasattr(train_data, "reset"):
                train_data.reset()
        _dispatch(handlers, "train_end", self)
        return self

    # --------------------------------------------------------------- warmup
    def _warmup(self, train_data, warmup):
        """AOT-compile the train path for every batch signature (see
        ``fit``). Parameters receive no optimizer step — only gradients
        (overwritten by the first real backward) and aux state move."""
        from ...base import get_env
        from ... import nd

        def _shape_sig(x):
            return (tuple(x.shape), str(getattr(x, "dtype", "?")))

        if self.train_step is not None:
            # fused path: drive the REAL jitted step per signature
            # (TrainStep.warmup marks the guard steady afterwards)
            with (_tel.span("estimator.warmup") if _tel._ENABLED
                  else _tel.NULL_SPAN):
                if warmup is True:
                    seen = []
                    seen_set = set()
                    cap = get_env("MXTPU_WARMUP_SCAN", 64, int)
                    for i, batch in enumerate(train_data):
                        if i >= cap:
                            break
                        data, label = _split_batch(batch)
                        inputs = tuple(data) if isinstance(
                            data, (list, tuple)) else (data,)
                        sig = tuple(_shape_sig(a) for a in inputs) + (
                            _shape_sig(label),)
                        if sig in seen_set:
                            continue
                        seen_set.add(sig)
                        seen.append(sig)
                    self.train_step.warmup(seen)
                else:
                    self.train_step.warmup(list(warmup))
            return
        with (_tel.span("estimator.warmup") if _tel._ENABLED
              else _tel.NULL_SPAN):
            if warmup is True:
                seen = set()
                cap = get_env("MXTPU_WARMUP_SCAN", 64, int)
                for i, batch in enumerate(train_data):
                    if i >= cap:
                        break
                    data, label = _split_batch(batch)
                    sig = (_shape_sig(data), _shape_sig(label))
                    if sig in seen:
                        continue
                    seen.add(sig)
                    self._warm_one(data, label)
            else:
                for data_spec, label_spec in warmup:
                    (dshape, ddt), (lshape, ldt) = data_spec, label_spec
                    self._warm_one(nd.zeros(dshape, dtype=ddt),
                                   nd.zeros(lshape, dtype=ldt))
        # hybridized nets: further new shapes are accidental recompiles
        co = getattr(self.net, "_cached_op", None)
        if co is not None:
            co._guard.mark_steady()

    def _warm_one(self, data, label):
        _tel.registry().counter("compile/warmup_compiles").inc()
        with autograd.record():
            pred = self.net(data)
            L = self.loss(pred, label)
        L.backward()

    def _fused_step(self, batch):
        """One fused-step dispatch: a pre-placed ``DeviceBatch`` from the
        prefetcher enters directly; raw batches flatten to the step's
        ``(input0, ..., label)`` calling convention."""
        from ...parallel.step import DeviceBatch

        with (_tel.span("estimator.train_step") if _tel._ENABLED
              else _tel.NULL_SPAN):
            if isinstance(batch, DeviceBatch):
                return self.train_step(batch)
            data, label = _split_batch(batch)
            inputs = tuple(data) if isinstance(data, (list, tuple)) \
                else (data,)
            return self.train_step(*inputs, label)

    @staticmethod
    def _epoch_iter(train_data, prefetch, feed=None):
        """One epoch's batch source: raw, or wrapped in the async device
        feed when ``prefetch`` is set (a fresh single-use pipeline per
        epoch — the staging thread dies with the epoch). With ``feed``
        (the fused ``TrainStep``), the prefetcher stages each batch onto
        the step's declared placements — sharded mesh layouts included —
        and yields pre-placed ``DeviceBatch`` objects."""
        if not prefetch:
            return train_data
        from ..data.prefetch import prefetch_to_device

        size = None if prefetch is True else int(prefetch)
        return prefetch_to_device(train_data, size=size, feed=feed)

    def _prepare_handlers(self, event_handlers, val_data, epochs, batches):
        handlers = list(_as_list(event_handlers) if event_handlers else [])
        if not any(isinstance(h, StoppingHandler) for h in handlers):
            handlers.append(StoppingHandler(max_epoch=epochs,
                                            max_batch=batches))
        if not any(isinstance(h, MetricHandler) for h in handlers):
            handlers.append(
                MetricHandler([self.train_loss_metric] + self.train_metrics)
            )
        if val_data is not None and not any(
            isinstance(h, ValidationHandler) for h in handlers
        ):
            handlers.append(ValidationHandler(val_data, self.evaluate))
        return handlers


# ------------------------------------------------------------------ helpers
def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _split_batch(batch):
    if isinstance(batch, (list, tuple)) and len(batch) >= 2:
        return batch[0], batch[1]
    if hasattr(batch, "data") and hasattr(batch, "label"):
        return batch.data[0], batch.label[0]
    raise MXNetError("cannot split batch into (data, label)")


def _batch_size(batch):
    data, _ = _split_batch(batch)
    if isinstance(data, NDArray):
        return data.shape[0]
    return len(data)


def _dispatch(handlers, event, estimator, **kwargs):
    for h in handlers:
        fn = getattr(h, event, None)
        if fn is not None and callable(fn):
            fn(estimator, **kwargs)
