"""Sequence bucketing for shape-stable batches (GluonNLP analogue).

A variable-length NLP feed is shape poison on an XLA backend: every
distinct ``(batch, seq_len)`` is a separate traced-and-compiled program.
The GluonNLP stack solved the throughput half with ``FixedBucketSampler``
(length-sorted buckets, bigger batches for shorter sequences) and the
``Pad`` batchify; here the same pair ALSO solves the compile half, because
padding to a fixed menu of bucket boundaries bounds the signature set the
training step ever sees:

    lengths = [len(s) for s in dataset]
    sampler = FixedBucketSampler(lengths, batch_size=32, num_buckets=8,
                                 ratio=0.5, shuffle=True, last_batch="pad")
    loader = DataLoader(dataset, batch_sampler=sampler,
                        batchify_fn=PadToBucket(sampler.bucket_keys),
                        prefetch_to_device=2)
    step.warmup(...)          # compile every bucket signature up front
    for tokens, valid_length, label in loader:   # shape-stable batches
        ...

``PadToBucket`` pads each batch to the smallest bucket boundary that
fits and emits a ``valid_length`` mask, so losses/attention can ignore
the pad tail; prefetch then stages already-padded, shape-stable batches.
"""

from __future__ import annotations

import numpy as _np

from ...base import MXNetError
from ...ndarray import array as nd_array
from ...ndarray.ndarray import NDArray
from .sampler import Sampler

__all__ = ["FixedBucketSampler", "PadToBucket"]


def _even_bucket_keys(lengths, num_buckets):
    """Constant-width bucket boundaries spanning [min_len, max_len],
    deduplicated ascending, always ending exactly at max_len."""
    lo, hi = int(min(lengths)), int(max(lengths))
    if num_buckets <= 1 or lo == hi:
        return [hi]
    step = (hi - lo) / num_buckets
    keys = sorted({int(round(lo + step * (i + 1)))
                   for i in range(num_buckets)})
    keys[-1] = hi
    return sorted(set(keys))


class FixedBucketSampler(Sampler):
    """Batch sampler: assign each sample to the smallest bucket whose
    boundary fits its length, batch within buckets.

    Parameters
    ----------
    lengths : sequence of int — per-sample sequence lengths.
    batch_size : int — batch size of the LONGEST bucket.
    num_buckets : int — number of constant-width buckets (ignored when
        ``bucket_keys`` is given).
    bucket_keys : explicit ascending bucket boundaries (optional).
    ratio : float in [0, 1] — the GluonNLP batch-scaling knob: bucket
        ``i`` gets ``max(batch_size, batch_size * ratio * max_key /
        key_i)`` samples, so shorter buckets run bigger batches and
        tokens-per-batch stays roughly constant. 0 disables scaling.
    shuffle : shuffle samples within buckets and the emitted batch order
        (driven by numpy's global RNG — seed for determinism).
    last_batch : what to do with a bucket's ragged final batch:
        ``'keep'`` (emit smaller batch — a fresh shape signature),
        ``'discard'`` (drop it), or ``'pad'`` (wrap the bucket's own
        indices to fill — shape-stable, slightly oversamples).
    """

    def __init__(self, lengths, batch_size, num_buckets=10, bucket_keys=None,
                 ratio=0.0, shuffle=False, last_batch="keep"):
        self._lengths = [int(l) for l in lengths]
        if not self._lengths:
            raise MXNetError("FixedBucketSampler needs at least one length")
        if last_batch not in ("keep", "discard", "pad"):
            raise MXNetError(
                f"last_batch must be keep/discard/pad, got {last_batch!r}")
        if not 0.0 <= ratio <= 1.0:
            raise MXNetError(f"ratio must be in [0, 1], got {ratio}")
        if bucket_keys is None:
            bucket_keys = _even_bucket_keys(self._lengths, num_buckets)
        else:
            bucket_keys = sorted(int(k) for k in bucket_keys)
        self.bucket_keys = bucket_keys
        max_key = bucket_keys[-1]
        self.batch_sizes = [
            max(int(batch_size),
                int(batch_size * ratio * max_key / key)) if ratio > 0
            else int(batch_size)
            for key in bucket_keys
        ]
        self._shuffle = bool(shuffle)
        self._last_batch = last_batch
        # bucket membership (index lists), one per key, in key order
        self._buckets = [[] for _ in bucket_keys]
        for i, length in enumerate(self._lengths):
            for b, key in enumerate(bucket_keys):
                if length <= key:
                    self._buckets[b].append(i)
                    break
            else:
                raise MXNetError(
                    f"sample {i} has length {length} > largest bucket key "
                    f"{max_key}; extend bucket_keys")

    def _bucket_batches(self, indices, size):
        batches = [indices[i:i + size]
                   for i in range(0, len(indices), size)]
        if batches and len(batches[-1]) < size:
            if self._last_batch == "discard":
                batches.pop()
            elif self._last_batch == "pad":
                # wrap the bucket's own indices to fill: shape-stable at
                # the cost of oversampling a few sequences
                short = batches[-1]
                need = size - len(short)
                filler = (indices * ((need // len(indices)) + 1))[:need]
                batches[-1] = short + filler
        return batches

    def __iter__(self):
        all_batches = []
        for bucket, size in zip(self._buckets, self.batch_sizes):
            if not bucket:
                continue
            indices = list(bucket)
            if self._shuffle:
                _np.random.shuffle(indices)
            all_batches.extend(self._bucket_batches(indices, size))
        if self._shuffle:
            _np.random.shuffle(all_batches)
        return iter(all_batches)

    def __len__(self):
        n = 0
        for bucket, size in zip(self._buckets, self.batch_sizes):
            if not bucket:
                continue
            if self._last_batch == "discard":
                n += len(bucket) // size
            else:
                n += (len(bucket) + size - 1) // size
        return n

    def signatures(self):
        """The exact ``(batch_size, bucket_key)`` shape menu this sampler
        emits — the warmup contract: compile one program per entry and the
        steady-state loop never compiles again."""
        sigs = []
        for bucket, size, key in zip(self._buckets, self.batch_sizes,
                                     self.bucket_keys):
            if not bucket:
                continue
            full, rem = divmod(len(bucket), size)
            if full and (size, key) not in sigs:
                sigs.append((size, key))
            if rem and self._last_batch == "keep" \
                    and (rem, key) not in sigs:
                sigs.append((rem, key))
            if rem and self._last_batch == "pad" and not full \
                    and (size, key) not in sigs:
                sigs.append((size, key))
        return sigs

    def stats(self) -> str:
        """Human-readable bucket occupancy (GluonNLP's ``__repr__``)."""
        lines = [f"FixedBucketSampler: {len(self)} batches, "
                 f"last_batch={self._last_batch}"]
        for bucket, size, key in zip(self._buckets, self.batch_sizes,
                                     self.bucket_keys):
            lines.append(
                f"  key<={key:<6d} batch_size={size:<5d} "
                f"samples={len(bucket)}")
        return "\n".join(lines)


class PadToBucket:
    """Batchify: pad each sequence to the smallest bucket boundary that
    fits the batch, emit a ``valid_length`` vector.

    Sample forms accepted:

    - a bare sequence (1-D list/array) -> ``(data, valid_length)``
    - a tuple ``(seq, *rest)`` -> ``(data, valid_length, *rest_batched)``
      where each ``rest`` element is padded alongside ``seq`` when it is
      per-token (same leading length), else plainly stacked (scalar or
      fixed-shape labels).

    ``pad_val`` fills the tail of ``seq``; ``label_pad_val`` fills
    per-token rest fields (mask-friendly default -1, so a masked loss can
    recover the pad mask from the label alone) — pass a sequence to give
    each rest field its own pad value (e.g. ``[0, -1]`` for
    ``(src, tgt, label)`` samples). ``valid_length=False`` drops the
    mask vector so the batch structure matches a step's exact
    ``(input0, ..., label)`` contract. Outputs are NDArrays by default;
    pass ``numpy=True`` inside forked DataLoader workers (device arrays
    are forbidden there — the parent converts).
    """

    def __init__(self, bucket_keys, pad_val=0, label_pad_val=-1,
                 valid_length=True, numpy=False):
        self.bucket_keys = sorted(int(k) for k in bucket_keys)
        self.pad_val = pad_val
        self.label_pad_val = label_pad_val
        self._valid_length = bool(valid_length)
        self._numpy = bool(numpy)

    def _key_for(self, max_len):
        for k in self.bucket_keys:
            if max_len <= k:
                return k
        raise MXNetError(
            f"batch has length {max_len} > largest bucket key "
            f"{self.bucket_keys[-1]}; extend bucket_keys")

    @staticmethod
    def _pad_one(seq, key, pad_val):
        a = _np.asarray(seq)
        out_shape = (key,) + a.shape[1:]
        out = _np.full(out_shape, pad_val, dtype=a.dtype)
        out[: a.shape[0]] = a
        return out

    def _wrap(self, a):
        return a if self._numpy else nd_array(a)

    def __call__(self, samples):
        if not samples:
            raise MXNetError("PadToBucket got an empty batch")
        tupled = isinstance(samples[0], (tuple, list)) and not isinstance(
            samples[0], _np.ndarray)
        seqs = [s[0] if tupled else s for s in samples]
        seqs = [s.asnumpy() if isinstance(s, NDArray) else _np.asarray(s)
                for s in seqs]
        lengths = [s.shape[0] for s in seqs]
        key = self._key_for(max(lengths))
        data = _np.stack(
            [self._pad_one(s, key, self.pad_val) for s in seqs])
        out = [self._wrap(data)]
        if self._valid_length:
            out.append(self._wrap(_np.asarray(lengths, dtype=_np.int32)))
        if tupled:
            nfields = len(samples[0])
            for f in range(1, nfields):
                field = [s[f] for s in samples]
                field = [x.asnumpy() if isinstance(x, NDArray)
                         else _np.asarray(x) for x in field]
                pv = self.label_pad_val
                if isinstance(pv, (list, tuple)):
                    pv = pv[f - 1]
                per_token = all(
                    x.ndim >= 1 and x.shape[0] == n
                    for x, n in zip(field, lengths))
                if per_token:
                    out.append(self._wrap(_np.stack([
                        self._pad_one(x, key, pv) for x in field])))
                else:
                    out.append(self._wrap(_np.stack(field)))
        return out if tupled else tuple(out)
