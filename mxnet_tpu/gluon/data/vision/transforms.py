"""Vision transforms (reference: ``gluon/data/vision/transforms.py``
[unverified]). Transforms run host-side on numpy/NDArray samples before the
device feed; shapes are HWC uint8 in, like the reference."""

from __future__ import annotations

import numpy as _np

from ....base import MXNetError
from ....ndarray.ndarray import NDArray
from ....ndarray import array as nd_array
from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential

__all__ = [
    "Compose", "Cast", "ToTensor", "Normalize", "RandomResizedCrop",
    "CenterCrop", "Resize", "RandomFlipLeftRight", "RandomFlipTopBottom",
    "RandomBrightness", "RandomContrast", "RandomSaturation", "RandomLighting",
    "RandomColorJitter", "RandomCrop",
]


def _to_numpy(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


class Compose(Sequential):
    """Chain transforms (reference: ``transforms.Compose``)."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(Block):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return nd_array(_to_numpy(x).astype(self._dtype))


class ToTensor(Block):
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def forward(self, x):
        x = _to_numpy(x).astype(_np.float32) / 255.0
        if x.ndim == 3:
            x = x.transpose(2, 0, 1)
        elif x.ndim == 4:
            x = x.transpose(0, 3, 1, 2)
        return nd_array(x)


class Normalize(Block):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = _np.asarray(mean, dtype=_np.float32)
        self._std = _np.asarray(std, dtype=_np.float32)

    def forward(self, x):
        x = _to_numpy(x)
        mean = self._mean.reshape((-1, 1, 1)) if self._mean.ndim else self._mean
        std = self._std.reshape((-1, 1, 1)) if self._std.ndim else self._std
        return nd_array((x - mean) / std)


def _resize(img, size, interp=1):
    """Nearest/bilinear resize on HWC numpy (no cv2 dependency)."""
    h, w = img.shape[:2]
    if isinstance(size, int):
        ow, oh = size, size
    else:
        ow, oh = size
    if (oh, ow) == (h, w):
        return img
    y = _np.linspace(0, h - 1, oh)
    x = _np.linspace(0, w - 1, ow)
    if interp == 0:  # nearest
        yi = _np.round(y).astype(int)
        xi = _np.round(x).astype(int)
        return img[yi][:, xi]
    y0 = _np.floor(y).astype(int)
    x0 = _np.floor(x).astype(int)
    y1 = _np.minimum(y0 + 1, h - 1)
    x1 = _np.minimum(x0 + 1, w - 1)
    wy = (y - y0)[:, None, None]
    wx = (x - x0)[None, :, None]
    img_f = img.astype(_np.float32)
    top = img_f[y0][:, x0] * (1 - wx) + img_f[y0][:, x1] * wx
    bot = img_f[y1][:, x0] * (1 - wx) + img_f[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out.astype(img.dtype)


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interpolation = interpolation

    def forward(self, x):
        img = _to_numpy(x)
        size = self._size
        if self._keep and isinstance(size, int):
            h, w = img.shape[:2]
            if h < w:
                size = (int(w * size / h), size)
            else:
                size = (size, int(h * size / w))
        return nd_array(_resize(img, size, self._interpolation))


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._interpolation = interpolation

    def forward(self, x):
        img = _to_numpy(x)
        h, w = img.shape[:2]
        cw, ch = self._size
        if h < ch or w < cw:
            img = _resize(img, (max(cw, w), max(ch, h)), self._interpolation)
            h, w = img.shape[:2]
        y0 = (h - ch) // 2
        x0 = (w - cw) // 2
        return nd_array(img[y0 : y0 + ch, x0 : x0 + cw])


class RandomCrop(Block):
    def __init__(self, size, pad=None, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._pad = pad
        self._interpolation = interpolation

    def forward(self, x):
        img = _to_numpy(x)
        if self._pad:
            p = self._pad
            img = _np.pad(img, ((p, p), (p, p), (0, 0)), mode="constant")
        h, w = img.shape[:2]
        cw, ch = self._size
        if h < ch or w < cw:
            img = _resize(img, (max(cw, w), max(ch, h)), self._interpolation)
            h, w = img.shape[:2]
        y0 = _np.random.randint(0, h - ch + 1)
        x0 = _np.random.randint(0, w - cw + 1)
        return nd_array(img[y0 : y0 + ch, x0 : x0 + cw])


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4.0, 4.0 / 3.0),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._scale = scale
        self._ratio = ratio
        self._interpolation = interpolation

    def forward(self, x):
        img = _to_numpy(x)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = _np.random.uniform(*self._scale) * area
            log_ratio = (_np.log(self._ratio[0]), _np.log(self._ratio[1]))
            aspect = _np.exp(_np.random.uniform(*log_ratio))
            cw = int(round(_np.sqrt(target_area * aspect)))
            ch = int(round(_np.sqrt(target_area / aspect)))
            if cw <= w and ch <= h:
                y0 = _np.random.randint(0, h - ch + 1)
                x0 = _np.random.randint(0, w - cw + 1)
                crop = img[y0 : y0 + ch, x0 : x0 + cw]
                return nd_array(_resize(crop, self._size, self._interpolation))
        # fallback: center crop
        return CenterCrop(self._size, self._interpolation).forward(nd_array(img))


class RandomFlipLeftRight(Block):
    def forward(self, x):
        img = _to_numpy(x)
        if _np.random.rand() < 0.5:
            img = img[:, ::-1]
        return nd_array(_np.ascontiguousarray(img))


class RandomFlipTopBottom(Block):
    def forward(self, x):
        img = _to_numpy(x)
        if _np.random.rand() < 0.5:
            img = img[::-1]
        return nd_array(_np.ascontiguousarray(img))


class _RandomJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0):
        super().__init__()
        self._b = brightness
        self._c = contrast
        self._s = saturation

    def forward(self, x):
        img = _to_numpy(x).astype(_np.float32)
        if self._b:
            alpha = 1.0 + _np.random.uniform(-self._b, self._b)
            img = img * alpha
        if self._c:
            alpha = 1.0 + _np.random.uniform(-self._c, self._c)
            gray_mean = img.mean()
            img = img * alpha + gray_mean * (1 - alpha)
        if self._s:
            alpha = 1.0 + _np.random.uniform(-self._s, self._s)
            gray = img @ _np.array([0.299, 0.587, 0.114], _np.float32)
            img = img * alpha + gray[..., None] * (1 - alpha)
        return nd_array(_np.clip(img, 0, 255))


class RandomBrightness(_RandomJitter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)


class RandomContrast(_RandomJitter):
    def __init__(self, contrast):
        super().__init__(contrast=contrast)


class RandomSaturation(_RandomJitter):
    def __init__(self, saturation):
        super().__init__(saturation=saturation)


class RandomColorJitter(_RandomJitter):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__(brightness, contrast, saturation)


class RandomLighting(Block):
    """AlexNet-style PCA noise."""

    _eigval = _np.array([55.46, 4.794, 1.148], _np.float32)
    _eigvec = _np.array(
        [[-0.5675, 0.7192, 0.4009],
         [-0.5808, -0.0045, -0.8140],
         [-0.5836, -0.6948, 0.4203]], _np.float32)

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        img = _to_numpy(x).astype(_np.float32)
        alpha = _np.random.normal(0, self._alpha, size=(3,)).astype(_np.float32)
        rgb = (self._eigvec * alpha * self._eigval).sum(axis=1)
        return nd_array(_np.clip(img + rgb, 0, 255))
