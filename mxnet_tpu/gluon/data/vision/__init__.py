"""Vision datasets + transforms (reference:
``python/mxnet/gluon/data/vision/`` [unverified])."""

from .datasets import *  # noqa: F401,F403
from . import transforms  # noqa: F401
from . import datasets

__all__ = datasets.__all__ + ["transforms"]
