"""Vision datasets (reference: ``gluon/data/vision/datasets.py``
[unverified]).

Zero-egress environment: datasets read standard files from ``root`` (the
reference's download cache layout); ``download`` raises with instructions if
files are absent. MNIST/FashionMNIST parse the IDX format; CIFAR10/100 parse
the python pickle batches.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as _np

from ....base import MXNetError
from ....ndarray import array as nd_array
from ..dataset import Dataset, ArrayDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100", "ImageFolderDataset"]


def _base_mnist_dir():
    return os.path.join(
        os.environ.get("MXNET_HOME", os.path.expanduser("~/.mxnet")), "datasets"
    )


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        self._transform = transform
        self._data = None
        self._label = None
        root = os.path.expanduser(root)
        self._root = root
        if not os.path.isdir(root):
            os.makedirs(root, exist_ok=True)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):  # pragma: no cover - abstract
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST handwritten digits. Expects IDX files (optionally .gz) in root."""

    _files = {
        True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
        False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
    }

    def __init__(self, root=None, train=True, transform=None):
        self._train = train
        root = root or os.path.join(_base_mnist_dir(), "mnist")
        super().__init__(root, transform)

    def _open(self, path):
        if os.path.exists(path):
            return open(path, "rb")
        if os.path.exists(path + ".gz"):
            return gzip.open(path + ".gz", "rb")
        raise MXNetError(
            f"MNIST file {path}[.gz] not found; this environment has no "
            f"network egress — place the IDX files under {self._root}"
        )

    def _get_data(self):
        image_file, label_file = self._files[self._train]
        with self._open(os.path.join(self._root, label_file)) as fin:
            magic, num = struct.unpack(">II", fin.read(8))
            label = _np.frombuffer(fin.read(), dtype=_np.uint8).astype(_np.int32)
        with self._open(os.path.join(self._root, image_file)) as fin:
            magic, num, rows, cols = struct.unpack(">IIII", fin.read(16))
            data = _np.frombuffer(fin.read(), dtype=_np.uint8)
            data = data.reshape(num, rows, cols, 1)
        self._data = data
        self._label = label


class FashionMNIST(MNIST):
    def __init__(self, root=None, train=True, transform=None):
        root = root or os.path.join(_base_mnist_dir(), "fashion-mnist")
        MNIST.__init__(self, root=root, train=train, transform=transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 from the python pickle batches in root/cifar-10-batches-py."""

    def __init__(self, root=None, train=True, transform=None):
        self._train = train
        root = root or os.path.join(_base_mnist_dir(), "cifar10")
        super().__init__(root, transform)

    def _batches(self):
        if self._train:
            return [f"data_batch_{i}" for i in range(1, 6)]
        return ["test_batch"]

    def _get_data(self):
        folder = os.path.join(self._root, "cifar-10-batches-py")
        if not os.path.isdir(folder):
            folder = self._root
        data, labels = [], []
        for name in self._batches():
            path = os.path.join(folder, name)
            if not os.path.exists(path):
                raise MXNetError(
                    f"CIFAR batch {path} not found; no network egress — "
                    f"extract cifar-10-python.tar.gz under {self._root}"
                )
            with open(path, "rb") as f:
                batch = pickle.load(f, encoding="latin1")
            data.append(
                batch["data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            )
            labels.extend(batch["labels"])
        self._data = _np.concatenate(data).astype(_np.uint8)
        self._label = _np.asarray(labels, dtype=_np.int32)


class CIFAR100(_DownloadedDataset):
    def __init__(self, root=None, fine_label=False, train=True, transform=None):
        self._train = train
        self._fine_label = fine_label
        root = root or os.path.join(_base_mnist_dir(), "cifar100")
        super().__init__(root, transform)

    def _get_data(self):
        folder = os.path.join(self._root, "cifar-100-python")
        if not os.path.isdir(folder):
            folder = self._root
        name = "train" if self._train else "test"
        path = os.path.join(folder, name)
        if not os.path.exists(path):
            raise MXNetError(
                f"CIFAR100 batch {path} not found; no network egress — "
                f"extract cifar-100-python.tar.gz under {self._root}"
            )
        with open(path, "rb") as f:
            batch = pickle.load(f, encoding="latin1")
        self._data = (
            batch["data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        ).astype(_np.uint8)
        key = "fine_labels" if self._fine_label else "coarse_labels"
        self._label = _np.asarray(batch[key], dtype=_np.int32)


class ImageFolderDataset(Dataset):
    """root/category/image.jpg layout (reference: ``ImageFolderDataset``)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                if os.path.splitext(filename)[1].lower() in self._exts:
                    self.items.append((os.path.join(path, filename), label))

    def __getitem__(self, idx):
        from .... import image as img_mod

        img = img_mod.imread(self.items[idx][0], self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
