"""DataLoader (reference: ``python/mxnet/gluon/data/dataloader.py``
[unverified]).

The reference forked worker *processes* that rebuilt NDArrays in shared
memory. Here batches are host-side numpy until the device feed (a jax
device_put at the end), so worker *threads* suffice: decode/augment/batchify
release the GIL inside numpy, and the thread pool + bounded prefetch queue
reproduces the reference's ``ThreadedIter`` pipeline without fork-unsafe
interaction with the TPU runtime (the reference itself had engine-fork
handlers for exactly that hazard)."""

from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as _np

from ...ndarray.ndarray import NDArray
from ...ndarray import array as nd_array
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: ``default_batchify_fn``)."""
    if isinstance(data[0], NDArray):
        return nd_array(_np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], (tuple, list)):
        return [default_batchify_fn(list(i)) for i in zip(*data)]
    data = _np.asarray(data)
    return nd_array(data)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=False, timeout=120):
        self._dataset = dataset
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is"
                )
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler is")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is"
            )
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._prefetch = max(
            0, int(prefetch) if prefetch is not None else 2 * self._num_workers
        )
        self._batchify_fn = batchify_fn or default_batchify_fn

    def __len__(self):
        return len(self._batch_sampler)

    def _load(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._load(indices)
            return
        # threaded pipeline: submit up to `prefetch` batches ahead
        with ThreadPoolExecutor(max_workers=self._num_workers) as pool:
            # bounded queue: feeder blocks when `prefetch` batches are pending
            futures = queue.Queue(maxsize=self._prefetch + 1)
            it = iter(self._batch_sampler)
            stop = threading.Event()

            def put_checked(item):
                # bounded put that keeps observing `stop` so an abandoned
                # iterator (break/exception in the consumer) never leaves
                # the feeder blocked forever on a full queue
                while not stop.is_set():
                    try:
                        futures.put(item, timeout=0.1)
                        return True
                    except queue.Full:
                        continue
                return False

            def feeder():
                try:
                    for indices in it:
                        if stop.is_set():
                            return
                        if not put_checked(pool.submit(self._load, indices)):
                            return
                finally:
                    put_checked(None)

            t = threading.Thread(target=feeder, daemon=True)
            t.start()
            try:
                while True:
                    fut = futures.get()
                    if fut is None:
                        break
                    yield fut.result(timeout=self._timeout)
            finally:
                stop.set()
