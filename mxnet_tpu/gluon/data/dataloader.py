"""DataLoader (reference: ``python/mxnet/gluon/data/dataloader.py``
[unverified]).

Two parallel backends, matching the reference's split:

- ``num_workers > 0`` (default): forked worker PROCESSES, batches come
  back as numpy through POSIX shared memory (the reference rebuilt
  NDArrays in shared memory the same way) and are device-fed in the
  parent. True parallelism for Python-heavy augmentation pipelines the
  GIL would serialize. Workers must not touch the device: datasets
  should yield numpy/python values (device arrays are converted in the
  parent) — the fork inherits the TPU runtime's sockets, so any child
  device call would corrupt the parent's session (the reference kept
  engine fork-handlers for exactly this hazard).
- ``thread_pool=True``: worker threads + bounded prefetch queue (the
  reference's ``ThreadedIter`` shape) — right when the work is
  numpy-bound (releases the GIL) or the dataset holds device arrays.

``pin_memory=True`` device_puts each batch as it is yielded (the TPU
analogue of pinned-host staging: the transfer is issued immediately,
async, so compute overlaps the next batch's host work).
"""

from __future__ import annotations

import multiprocessing as _mp
import os
import pickle
import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from multiprocessing import shared_memory as _shm

import numpy as _np

from ... import telemetry as _tel
from ...ndarray.ndarray import NDArray
from ...ndarray import array as nd_array
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: ``default_batchify_fn``)."""
    if isinstance(data[0], NDArray):
        return nd_array(_np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], (tuple, list)):
        return [default_batchify_fn(list(i)) for i in zip(*data)]
    data = _np.asarray(data)
    return nd_array(data)


def _np_batchify(data):
    """Worker-side batchify: numpy only — a forked worker must never
    touch the device (it inherits the parent runtime's sockets), so
    device NDArrays from the dataset are a hard error with the fix
    named instead of a silent session-corrupting transfer."""
    first = data[0]
    if isinstance(first, NDArray):
        raise TypeError(
            "dataset yielded device NDArrays inside a forked DataLoader "
            "worker; device access from the child would corrupt the "
            "parent's TPU session. Yield numpy/python values, or use "
            "DataLoader(..., thread_pool=True)"
        )
    if isinstance(first, (tuple, list)):
        return [_np_batchify(list(i)) for i in zip(*data)]
    return _np.asarray(data)


def _assert_no_device(tree):
    """A forked worker's batch must be device-free — custom batchify_fns
    returning NDArrays would otherwise pickle device arrays through the
    inherited TPU session (the corruption the default path refuses)."""
    if isinstance(tree, NDArray):
        raise TypeError(
            "batchify_fn returned device NDArrays inside a forked "
            "DataLoader worker; return numpy/python values (the parent "
            "converts to device arrays), or use thread_pool=True"
        )
    if isinstance(tree, dict):
        for t in tree.values():
            _assert_no_device(t)
    elif isinstance(tree, (list, tuple)):
        for t in tree:
            _assert_no_device(t)


def _to_device(batch):
    if isinstance(batch, list):
        return [_to_device(b) for b in batch]
    return nd_array(batch)


_FORK_WARNED = [False]


def _warn_fork_after_runtime():
    """One-time warning when worker processes fork AFTER the JAX runtime
    initialized: locked runtime mutexes are copied into the child and can
    deadlock it (advisor round 3; the reference kept engine fork-handlers
    for the same hazard)."""
    if _FORK_WARNED[0]:
        return
    try:
        from jax._src import xla_bridge as _xb

        initialized = bool(getattr(_xb, "_backends", None))
    except Exception:  # noqa: BLE001 - private API moved
        initialized = False
    if initialized:
        import warnings

        warnings.warn(
            "DataLoader is forking worker processes after the JAX runtime "
            "started; mutexes held by runtime threads at fork time are "
            "copied locked into the children and may deadlock them. "
            "Create DataLoaders before the first device computation, or "
            "use thread_pool=True.",
            RuntimeWarning,
            stacklevel=4,
        )
        _FORK_WARNED[0] = True


# ---------------------------------------------------------------- mp worker
def _pack(tree):
    """numpy tree -> (spec, shm list): arrays ride shared memory, not the
    pickle stream (one copy on each side instead of pickle+copy)."""
    shms = []

    def walk(node):
        if isinstance(node, _np.ndarray) and node.nbytes > 0:
            s = _shm.SharedMemory(create=True, size=node.nbytes)
            view = _np.ndarray(node.shape, node.dtype, buffer=s.buf)
            view[...] = node
            shms.append(s)
            return ("arr", node.shape, str(node.dtype), s.name)
        if isinstance(node, list):
            return ("list", [walk(n) for n in node])
        return ("obj", node)

    return walk(tree), shms


def _unpack(spec):
    kind = spec[0]
    if kind == "arr":
        _, shape, dtype, name = spec
        s = _shm.SharedMemory(name=name)
        try:
            out = _np.ndarray(shape, dtype, buffer=s.buf).copy()
        finally:
            s.close()
            s.unlink()
        return out
    if kind == "list":
        return [_unpack(n) for n in spec[1]]
    return spec[1]


def _worker_loop(dataset, index_q, data_q, seed, batchify_fn):
    # child of fork: numpy-only territory (device calls are forbidden).
    # batchify_fn is fork-inherited; a custom one must return numpy/python
    # values only (the parent converts to device arrays)
    _np.random.seed(seed)
    batchify = batchify_fn or _np_batchify
    while True:
        job = index_q.get()
        if job is None:
            return
        bid, indices = job
        try:
            batch = batchify([dataset[i] for i in indices])
            _assert_no_device(batch)
            spec, shms = _pack(batch)
            data_q.put((bid, "ok", spec))
            for s in shms:
                s.close()
                # the parent unlinks after rebuilding; deregister here so
                # this process's resource tracker doesn't warn about (and
                # double-unlink) segments it no longer owns
                try:
                    from multiprocessing import resource_tracker
                    resource_tracker.unregister(s._name, "shared_memory")
                except Exception:  # noqa: BLE001 - tracker impl detail
                    pass
        except Exception as e:  # noqa: BLE001 - forward to the parent
            data_q.put((bid, "err", pickle.dumps(e)))


class DataLoader:
    """See module docstring for backend selection.

    Fork hazards (advisor round 3): ``num_workers > 0`` without
    ``thread_pool`` fork()s the parent. Forking AFTER the JAX/TPU
    runtime has started is dangerous beyond device access: any mutex a
    runtime thread holds at fork time (allocator, logging, XLA
    compilation) is copied LOCKED into the child and can deadlock it.
    Create your DataLoaders (or take one batch) before the first device
    computation, or pass ``thread_pool=True``. A one-time warning fires
    when the fork pool is created after runtime init.

    ``persistent_workers=True`` (default) forks ONCE and reuses the pool
    across epochs — the dataset is snapshotted at the first fork, so
    datasets must be immutable across epochs (epoch-dependent state like
    ``set_epoch`` patterns is silently ignored). Pass
    ``persistent_workers=False`` for the reference's re-fork-per-iterator
    semantics: each epoch sees the dataset's current state, at the cost
    of a fork per epoch.
    """

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=False, timeout=120,
                 persistent_workers=True, prefetch_to_device=None):
        self._dataset = dataset
        self._timeout = timeout
        # prefetch_to_device=N (or True): each epoch's iterator is wrapped
        # in gluon.data.prefetch.prefetch_to_device — a background thread
        # keeps up to N batches staged ON DEVICE so the next transfer
        # overlaps the current step's compute (True reads
        # MXTPU_PREFETCH_DEFAULT). Distinct from `prefetch`, which bounds
        # HOST batches in flight inside the worker pool.
        self._prefetch_device = prefetch_to_device
        self._persistent_workers = bool(persistent_workers)
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is"
                )
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler is")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is"
            )
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._thread_pool = bool(thread_pool)
        self._pin_memory = bool(pin_memory)
        self._pin_device_id = int(pin_device_id)
        self._prefetch = max(
            0, int(prefetch) if prefetch is not None else 2 * self._num_workers
        )
        self._batchify_fn = batchify_fn or default_batchify_fn

    def __len__(self):
        return len(self._batch_sampler)

    def _load(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def _maybe_pin(self, batch):
        if not self._pin_memory:
            return batch
        import jax

        dev = jax.devices()[self._pin_device_id] \
            if self._pin_device_id < len(jax.devices()) else jax.devices()[0]

        def put(b):
            if isinstance(b, list):
                return [put(x) for x in b]
            if isinstance(b, NDArray):
                return NDArray(jax.device_put(b.data, dev))
            return b

        return put(batch)

    def __iter__(self):
        if self._prefetch_device:
            from .prefetch import prefetch_to_device as _ptd

            size = None if self._prefetch_device is True \
                else int(self._prefetch_device)
            return _ptd(self._iter_host(), size=size)
        return self._iter_host()

    def _iter_host(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                if _tel._ENABLED:
                    with _tel.span("dataloader.load",
                                   {"batch": len(indices)}):
                        batch = self._load(indices)
                else:
                    batch = self._load(indices)
                yield self._maybe_pin(batch)
            return
        if self._thread_pool:
            yield from self._iter_threaded()
        else:
            yield from self._iter_mp()

    # --------------------------------------------------------- thread pool
    def _iter_threaded(self):
        with ThreadPoolExecutor(max_workers=self._num_workers) as pool:
            # bounded queue: feeder blocks when `prefetch` batches are pending
            futures = queue.Queue(maxsize=self._prefetch + 1)
            it = iter(self._batch_sampler)
            stop = threading.Event()

            def put_checked(item):
                # bounded put that keeps observing `stop` so an abandoned
                # iterator (break/exception in the consumer) never leaves
                # the feeder blocked forever on a full queue
                while not stop.is_set():
                    try:
                        futures.put(item, timeout=0.1)
                        return True
                    except queue.Full:
                        continue
                return False

            def feeder():
                try:
                    for indices in it:
                        if stop.is_set():
                            return
                        if not put_checked(pool.submit(self._load, indices)):
                            return
                finally:
                    put_checked(None)

            t = threading.Thread(target=feeder, daemon=True)
            t.start()
            try:
                while True:
                    fut = futures.get()
                    if fut is None:
                        break
                    if _tel._ENABLED:
                        # time only the consumer-side wait: worker compute
                        # already overlaps; the wait IS the input stall
                        with _tel.span("dataloader.wait"):
                            batch = fut.result(timeout=self._timeout)
                    else:
                        batch = fut.result(timeout=self._timeout)
                    yield self._maybe_pin(batch)
            finally:
                stop.set()

    # ------------------------------------------------------ fork processes
    def _ensure_pool(self):
        """Spawn the worker pool ONCE and reuse it across epochs
        (persistent workers): forking a large parent per epoch costs more
        than a short epoch's worth of loading."""
        pool = getattr(self, "_mp_pool", None)
        if pool is not None:
            if all(p.is_alive() for p in pool[0]):
                return pool
            # partially dead: retire the survivors before rebuilding, or
            # they stay blocked on the orphaned queue forever
            old_workers, old_index_q, _old_dq = pool
            for p in old_workers:
                if p.is_alive():
                    try:
                        old_index_q.put_nowait(None)
                    except Exception:  # noqa: BLE001 - full/closed queue
                        pass
            for p in old_workers:
                p.join(timeout=0.5)
                if p.is_alive():
                    p.terminate()
        _warn_fork_after_runtime()
        ctx = _mp.get_context("fork")
        index_q = ctx.Queue()
        data_q = ctx.Queue()
        workers = []
        custom = self._batchify_fn \
            if self._batchify_fn is not default_batchify_fn else None
        for w in range(self._num_workers):
            p = ctx.Process(
                target=_worker_loop,
                args=(self._dataset, index_q, data_q,
                      _np.random.randint(0, 2 ** 31 - 1), custom),
                daemon=True,
            )
            p.start()
            workers.append(p)
        self._mp_pool = (workers, index_q, data_q)
        self._mp_next_id = 0
        return self._mp_pool

    def _shutdown_pool(self):
        pool = getattr(self, "_mp_pool", None)
        if pool is None:
            return
        workers, index_q, data_q = pool
        for _w in workers:
            try:
                index_q.put(None)
            except Exception:  # noqa: BLE001 - interpreter shutdown
                pass
        for p in workers:
            p.join(timeout=0.5)
            if p.is_alive():
                p.terminate()
        # unlink any results still queued — their segments were already
        # deregistered from the workers' resource trackers, so nobody
        # else will ever free them
        try:
            self._drain_stale(data_q)
        except Exception:  # noqa: BLE001 - interpreter shutdown
            pass
        self._mp_pool = None

    def __del__(self):
        self._shutdown_pool()

    @staticmethod
    def _discard(spec):
        """Unlink the shared memory of an unclaimed result."""
        if spec[0] == "arr":
            try:
                seg = _shm.SharedMemory(name=spec[3])
                seg.close()
                seg.unlink()
            except FileNotFoundError:
                pass
        elif spec[0] == "list":
            for n in spec[1]:
                DataLoader._discard(n)

    def _drain_stale(self, data_q):
        """Consume results left over from an abandoned iterator, freeing
        their shared-memory segments."""
        while True:
            try:
                _bid, status, payload = data_q.get_nowait()
            except queue.Empty:
                return
            if status == "ok":
                self._discard(payload)

    def _iter_mp(self):
        if not self._persistent_workers:
            # reference semantics: a fresh fork per iterator, so the
            # workers see the dataset's CURRENT state each epoch
            self._shutdown_pool()
        elif (getattr(self, "_mp_pool", None) is not None
              and not getattr(self, "_warned_persistent", False)):
            # pool reuse across epochs: workers hold the dataset as
            # snapshotted at the first fork, so epoch-dependent dataset
            # mutation in the parent is silently invisible to them —
            # a behavior change from the reference's fork-per-iterator.
            import warnings
            self._warned_persistent = True
            warnings.warn(
                "DataLoader(persistent_workers=True) reuses the worker "
                "pool across epochs; the dataset was snapshotted at the "
                "first fork, so per-epoch dataset mutation will not be "
                "seen by workers. Pass persistent_workers=False for the "
                "reference's fork-per-iterator semantics.",
                stacklevel=3)
        workers, index_q, data_q = self._ensure_pool()
        self._drain_stale(data_q)
        batches = list(self._batch_sampler)
        base = self._mp_next_id  # unique ids across epochs
        self._mp_next_id += len(batches)
        ahead = min(len(batches), self._num_workers + self._prefetch)
        for i in range(ahead):
            index_q.put((base + i, batches[i]))
        try:
            yield from self._mp_consume(workers, index_q, data_q, batches,
                                        base, ahead)
        finally:
            # abandoned mid-epoch (break/exception): results already on
            # the queue would leak their shm segments; reap them now (a
            # worker still computing is reaped by the next epoch's drain)
            self._drain_stale(data_q)

    def _mp_consume(self, workers, index_q, data_q, batches, base, ahead):
        import time as _time

        pending = {}
        next_submit = ahead
        for want_i in range(len(batches)):
            want = base + want_i
            deadline = _time.monotonic() + self._timeout
            with (_tel.span("dataloader.wait") if _tel._ENABLED
                  else _tel.NULL_SPAN):
                self._mp_wait(want, pending, workers, data_q, deadline)
            if next_submit < len(batches):
                index_q.put((base + next_submit, batches[next_submit]))
                next_submit += 1
            yield self._maybe_pin(_to_device(pending.pop(want)))

    def _mp_wait(self, want, pending, workers, data_q, deadline):
        import time as _time

        while want not in pending:
            try:
                bid, status, payload = data_q.get(timeout=1.0)
            except queue.Empty:
                dead = [i for i, p in enumerate(workers)
                        if not p.is_alive()]
                if dead:
                    codes = [workers[i].exitcode for i in dead]
                    raise RuntimeError(
                        f"DataLoader worker(s) {dead} died "
                        f"(exitcode {codes}); restart the iterator"
                    )
                if _time.monotonic() > deadline:
                    raise RuntimeError(
                        f"DataLoader batch {want} timed out after "
                        f"{self._timeout}s (workers alive but stuck)"
                    )
                continue
            if status == "err":
                raise pickle.loads(payload)
            pending[bid] = _unpack(payload)
