"""Data loading (reference: ``python/mxnet/gluon/data/`` [unverified])."""

from .dataset import *  # noqa: F401,F403
from .sampler import *  # noqa: F401,F403
from .dataloader import *  # noqa: F401,F403
from .prefetch import *  # noqa: F401,F403
from .bucketing import *  # noqa: F401,F403
from . import vision  # noqa: F401

from . import dataset, sampler, dataloader, prefetch, bucketing

__all__ = (dataset.__all__ + sampler.__all__ + dataloader.__all__ +
           prefetch.__all__ + bucketing.__all__ + ["vision"])
