"""Asynchronous device-feed pipeline: prefetch batches TO THE DEVICE.

The reference's ThreadedIter/prefetcher (SURVEY §3.3) overlapped disk +
augmentation with training; its TPU-shaped gap is the host->device leg:
``DataLoader`` (even with worker processes) hands the consumer HOST
batches, and every ``TrainStep.__call__`` then pays a synchronous
reshape/split plus per-input ``device_put`` before it can dispatch. On a
dispatch-latency-bound backend that host work sits squarely on the
critical path.

``prefetch_to_device`` moves it off: a background thread pulls host
batches from any iterable, applies the CONSUMER'S exact placement —
``feed.device_put_batch`` when the feed (a ``TrainStep``) publishes its
contract via ``feed_spec()``, plain default-device ``device_put``
otherwise — and keeps a bounded queue of ``size`` batches already in
flight on device, so the next batch's transfer overlaps the current
step's compute::

    pf = prefetch_to_device(loader, size=2, feed=step)
    for batch in pf:          # DeviceBatch, already split + sharded
        loss = step(batch)    # __call__ fast path: dispatch only

Shutdown is clean in every direction: source exhaustion ends the
iterator; a worker-side exception is re-raised at the consumer's next
pull; abandoning the iterator (``break``, error, ``close()``, GC)
unblocks and retires the worker thread.

Telemetry (always-on registry metrics; spans only when enabled):
``input/wait_ms`` histogram (time the consumer blocked waiting for a
staged batch — the residual input stall after overlap), ``input/
queue_depth`` gauge, ``input/batches`` counter, and an ``input.wait``
span per pull.
"""

from __future__ import annotations

import queue
import threading
import time as _time

import numpy as _np

from ... import telemetry as _tel
from ...base import get_env
from ...ndarray.ndarray import NDArray

__all__ = ["prefetch_to_device", "PrefetchIterator"]

_OK, _ERR, _END = 0, 1, 2


def _default_place(batch):
    """Consumer-agnostic placement: numpy leaves -> device NDArrays on the
    default device (structure preserved), issued from the worker thread so
    the transfer overlaps the consumer's compute."""
    import jax

    if isinstance(batch, (list, tuple)):
        return type(batch)(_default_place(b) for b in batch)
    if isinstance(batch, NDArray):
        return NDArray(jax.device_put(batch.data))
    if isinstance(batch, _np.ndarray):
        return NDArray(jax.device_put(batch))
    return batch


def _bounded_put(q, stop, item) -> bool:
    """Bounded put that keeps observing the stop flag so an abandoned
    consumer never leaves the worker blocked on a full queue."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.05)
            return True
        except queue.Full:
            continue
    return False


def _worker(loader, q, stop, place):
    # module-level on purpose: the thread must hold NO reference to the
    # PrefetchIterator, or an abandoned (GC'd) iterator could never fire
    # __del__/close and the worker would leak
    it = None
    try:
        it = iter(loader)
        while not stop.is_set():
            try:
                batch = next(it)
            except StopIteration:
                break
            staged = place(batch)
            if not _bounded_put(q, stop, (_OK, staged)):
                return
    except BaseException as e:  # noqa: BLE001 - forward to consumer
        _bounded_put(q, stop, (_ERR, e))
        return
    finally:
        if it is not None and hasattr(it, "close"):
            try:
                it.close()
            except Exception:  # noqa: BLE001 - source teardown
                pass
    _bounded_put(q, stop, (_END, None))


class PrefetchIterator:
    """Single-use iterator over ``loader`` with device-side staging.

    Prefer the ``prefetch_to_device`` factory; see the module docstring
    for the contract. Also a context manager (``with`` closes it).
    """

    def __init__(self, loader, size, feed=None):
        if size < 1:
            raise ValueError(f"prefetch size must be >= 1, got {size}")
        self._loader = loader
        self._size = int(size)
        if feed is not None and not hasattr(feed, "device_put_batch"):
            raise TypeError(
                f"feed {type(feed).__name__} has no device_put_batch(); "
                "pass a TrainStep (or feed=None for default placement)"
            )
        if feed is not None:
            def place(batch):
                flat = tuple(batch) if isinstance(batch, (list, tuple)) \
                    else (batch,)
                return feed.device_put_batch(flat)
        else:
            place = _default_place
        # maxsize bounds DEVICE-resident batches: `size` staged in the
        # queue plus at most one held by the worker while it blocks in put
        self._q: queue.Queue = queue.Queue(maxsize=self._size)
        self._stop = threading.Event()
        self._closed = False
        self._thread = threading.Thread(
            target=_worker, args=(loader, self._q, self._stop, place),
            name="mxtpu-prefetch", daemon=True,
        )
        self._thread.start()

    # ----------------------------------------------------------- consumer
    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        if _tel._ENABLED:
            with _tel.span("input.wait", {"queued": self._q.qsize()}):
                kind, payload, wait_s = self._get()
        else:
            kind, payload, wait_s = self._get()
        reg = _tel.registry()
        reg.histogram("input/wait_ms").observe(wait_s * 1e3)
        reg.gauge("input/queue_depth").set(self._q.qsize())
        if kind == _OK:
            reg.counter("input/batches").inc()
            return payload
        self.close()
        if kind == _ERR:
            raise payload
        raise StopIteration

    def _get(self):
        t0 = _time.perf_counter()
        while True:
            try:
                kind, payload = self._q.get(timeout=1.0)
                return kind, payload, _time.perf_counter() - t0
            except queue.Empty:
                if not self._thread.is_alive():
                    # the worker can only exit after queueing _OK/_ERR/_END,
                    # so an empty queue here means those were drained by a
                    # concurrent close — treat as end of data
                    return _END, None, _time.perf_counter() - t0

    def __len__(self):
        return len(self._loader)

    # ------------------------------------------------------------ teardown
    def close(self):
        """Stop the worker and drop staged batches; idempotent."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        # drain so a worker blocked in put() can observe the stop flag
        self._drain()
        self._thread.join(timeout=5.0)
        self._drain()  # anything queued between first drain and exit

    def _drain(self):
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                return

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter shutdown
            pass


def prefetch_to_device(loader, size=None, feed=None) -> PrefetchIterator:
    """Wrap ``loader`` in a background device-staging pipeline.

    Parameters
    ----------
    loader : any iterable of batches (``DataLoader``, generator, list)
    size : bound on staged device-resident batches; ``None`` reads
        ``MXTPU_PREFETCH_DEFAULT`` (default 2). 2 suffices to overlap
        transfer with compute; raise it only for bursty per-batch cost.
    feed : optional consumer placement contract — an object with
        ``device_put_batch((input0, ..., label))`` (``TrainStep``). The
        staged batches then take ``__call__``'s pre-placed fast path.
        Without a feed, leaves go to the default device unsharded.
    """
    if size is None:
        size = get_env("MXTPU_PREFETCH_DEFAULT", 2, int)
    return PrefetchIterator(loader, int(size), feed)
