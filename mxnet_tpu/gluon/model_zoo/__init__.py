"""Model zoo (reference: ``python/mxnet/gluon/model_zoo/`` [unverified])."""

from . import vision  # noqa: F401

__all__ = ["vision"]
