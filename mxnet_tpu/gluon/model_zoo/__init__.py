"""Model zoo (reference: ``python/mxnet/gluon/model_zoo/`` [unverified];
language models mirror the GluonNLP-era workloads in BASELINE.md)."""

from . import vision  # noqa: F401
from . import bert  # noqa: F401
from . import transformer  # noqa: F401
from . import ssd  # noqa: F401
from . import faster_rcnn  # noqa: F401

__all__ = ["vision", "bert", "transformer", "ssd", "faster_rcnn"]
