"""Faster R-CNN two-stage detector (reference workload: the rcnn example
family over ``src/operator/contrib/proposal.cc`` + ROIAlign, and GluonCV
``faster_rcnn`` [unverified]; the second half of BASELINE config 5).

TPU-first shape discipline end to end:
- the RPN emits a STATIC ``rpn_post_nms_top_n`` proposals per image
  (suppressed slots ride along with score -1 — no dynamic compaction);
- second-stage sampling (``rcnn_target_sampler``) is deterministic
  top-by-IoU with static fg/bg counts;
- ROI pooling uses the batched (B, K, 4) ROIAlign fast path (no per-ROI
  whole-image gather);
- the whole train step (backbone -> RPN -> proposal -> sample -> pool ->
  heads) stages into ONE XLA program under hybridize()/TrainStep.
"""

from __future__ import annotations

from ..block import HybridBlock
from ..nn import Activation, BatchNorm, Conv2D, Dense, HybridSequential, \
    MaxPool2D

__all__ = ["FasterRCNN", "faster_rcnn_tiny"]


def _down_block(channels):
    blk = HybridSequential()
    for _ in range(2):
        blk.add(Conv2D(channels, kernel_size=3, padding=1),
                BatchNorm(in_channels=channels),
                Activation("relu"))
    blk.add(MaxPool2D(pool_size=2, strides=2))
    return blk


class FasterRCNN(HybridBlock):
    """Configurable two-stage detector.

    Parameters
    ----------
    num_classes : foreground classes (background is implicit class 0)
    channels : backbone down-block widths; stride = 2**len(channels)
    scales / ratios : RPN anchor shapes in feature-stride units
    rpn_post_nms_top_n : static proposal count per image
    num_sample / pos_ratio / pos_iou_thresh : second-stage sampler config
    """

    def __init__(self, num_classes, channels=(16, 32), scales=(2, 4),
                 ratios=(0.5, 1, 2), rpn_channels=64,
                 rpn_pre_nms_top_n=256, rpn_post_nms_top_n=64,
                 rpn_nms_thresh=0.7, rpn_min_size=4,
                 num_sample=32, pos_ratio=0.25, pos_iou_thresh=0.5,
                 roi_size=(7, 7), top_units=128, **kwargs):
        super().__init__(**kwargs)
        self._num_classes = num_classes
        self._stride = 2 ** len(channels)
        self._scales = tuple(scales)
        self._ratios = tuple(ratios)
        self._num_anchors = len(scales) * len(ratios)
        self._rpn_pre = int(rpn_pre_nms_top_n)
        self._rpn_post = int(rpn_post_nms_top_n)
        self._rpn_nms = float(rpn_nms_thresh)
        self._rpn_min = float(rpn_min_size)
        self._num_sample = int(num_sample)
        self._pos_ratio = float(pos_ratio)
        self._pos_iou = float(pos_iou_thresh)
        self._roi_size = tuple(roi_size)
        with self.name_scope():
            self.backbone = HybridSequential(prefix="backbone_")
            for c in channels:
                self.backbone.add(_down_block(c))
            A = self._num_anchors
            self.rpn_conv = Conv2D(rpn_channels, kernel_size=3, padding=1,
                                   activation="relu", prefix="rpnconv_")
            self.rpn_cls = Conv2D(2 * A, kernel_size=1, prefix="rpncls_")
            self.rpn_box = Conv2D(4 * A, kernel_size=1, prefix="rpnbox_")
            self.top = Dense(top_units, activation="relu", prefix="top_")
            self.rcnn_cls = Dense(num_classes + 1, prefix="rcnncls_")
            self.rcnn_box = Dense(4, prefix="rcnnbox_")

    # ------------------------------------------------------------ stages
    def _rpn(self, F, x):
        feat = self.backbone(x)
        r = self.rpn_conv(feat)
        rpn_cls = self.rpn_cls(r)   # (B, 2A, Hf, Wf) raw scores
        rpn_box = self.rpn_box(r)   # (B, 4A, Hf, Wf)
        B = rpn_cls.shape[0]
        A = self._num_anchors
        Hf, Wf = rpn_cls.shape[2], rpn_cls.shape[3]
        # per-anchor {bg, fg} softmax, reference SoftmaxActivation layout
        prob = F.softmax(rpn_cls.reshape(B, 2, A, Hf, Wf), axis=1)
        prob = prob.reshape(B, 2 * A, Hf, Wf)
        return feat, rpn_cls, rpn_box, prob

    def _proposals(self, F, prob, rpn_box, im_hw):
        # im_info rows [img_h, img_w, scale] built as a traced constant —
        # the model takes same-sized images per batch (static shapes)
        import jax.numpy as jnp
        from ...ndarray.ndarray import NDArray

        B = prob.shape[0]
        raw = jnp.broadcast_to(
            jnp.asarray([float(im_hw[0]), float(im_hw[1]), 1.0],
                        jnp.float32), (B, 3),
        )
        im_info = NDArray(raw)
        return F.Proposal(
            prob, rpn_box, im_info,
            rpn_pre_nms_top_n=self._rpn_pre,
            rpn_post_nms_top_n=self._rpn_post,
            threshold=self._rpn_nms, rpn_min_size=self._rpn_min,
            scales=self._scales, ratios=self._ratios,
            feature_stride=self._stride,
        )

    def _heads(self, F, feat, rois_xy):
        # rois_xy (B, K, 4) pixel coords -> batched ROIAlign on the feature
        pooled = F.ROIAlign(
            feat, rois_xy, pooled_size=self._roi_size,
            spatial_scale=1.0 / self._stride, sample_ratio=2,
        )  # (B, K, C, ph, pw)
        B, K = pooled.shape[0], pooled.shape[1]
        flat = pooled.reshape(B * K, -1)
        t = self.top(flat)
        cls = self.rcnn_cls(t).reshape(B, K, self._num_classes + 1)
        box = self.rcnn_box(t).reshape(B, K, 4)
        return cls, box

    # ----------------------------------------------------------- forward
    def hybrid_forward(self, F, x, gt_boxes=None):
        """Training (``gt_boxes`` (B, M, 5) rows [cls, x1, y1, x2, y2],
        cls < 0 padding): returns (rcnn_cls_pred, rcnn_box_pred,
        cls_targets, box_targets, box_masks, rpn_cls_scores, rpn_box_pred,
        rois). Inference (gt None): returns (rois, rcnn_cls_pred,
        rcnn_box_pred) over all proposals."""
        im_hw = (float(x.shape[2]), float(x.shape[3]))  # NCHW input
        feat, rpn_cls, rpn_box, prob = self._rpn(F, x)
        rois = self._proposals(F, prob, rpn_box, im_hw)  # (B, K, 5)
        if gt_boxes is None:
            cls, box = self._heads(F, feat, rois[:, :, 1:5])
            return rois, cls, box
        # append gt boxes PLUS deterministic jittered copies to the
        # proposals before sampling (the reference recipe appends gt and
        # samples randomly; with a deterministic sampler the jitter is
        # what gives the classifier foreground VARIETY — trained only on
        # exact gt boxes it learns a razor-thin fg boundary that nothing
        # at inference clears). Padding gts are all-zero boxes with IoU 0.
        gt_as_rois = gt_boxes[:, :, 1:5] * (gt_boxes[:, :, :1] >= 0)
        x1, y1, x2, y2 = (gt_as_rois[:, :, 0:1], gt_as_rois[:, :, 1:2],
                          gt_as_rois[:, :, 2:3], gt_as_rois[:, :, 3:4])
        w_, h_ = x2 - x1, y2 - y1
        jittered = []
        for dx, dy, ds in ((0.08, -0.06, 0.1), (-0.07, 0.08, -0.1),
                           (0.05, 0.05, 0.15)):
            jittered.append(F.concat(
                x1 + dx * w_ - ds * w_ / 2, y1 + dy * h_ - ds * h_ / 2,
                x2 + dx * w_ + ds * w_ / 2, y2 + dy * h_ + ds * h_ / 2,
                dim=-1,
            ))
        cand = F.concat(rois[:, :, 1:5], gt_as_rois, *jittered, dim=1)
        sampled, cls_t, box_t, box_m = F.rcnn_target_sampler(
            cand, gt_boxes, num_sample=self._num_sample,
            pos_ratio=self._pos_ratio, pos_iou_thresh=self._pos_iou,
        )
        cls, box = self._heads(F, feat, sampled)
        return cls, box, cls_t, box_t, box_m, rpn_cls, rpn_box, rois

    # ------------------------------------------------------- rpn targets
    def rpn_dense_targets(self, gt_boxes, im_hw, feat_hw,
                          negative_mining_ratio=-1.0, cls_preds=None):
        """Dense per-anchor RPN training targets via MultiBoxTarget
        (class-agnostic). Default is the DENSE loss — weight foregrounds
        up in the classification loss (e.g. ``1 + 19*(ct > 0)``) so the
        easy backgrounds don't swamp them. Deterministic hard-negative
        mining (``negative_mining_ratio > 0``) is available but leaves
        never-mined anchors unconstrained, which poisons the proposal
        ranking — the reference avoided that with RANDOM per-iteration
        sampling, which a static graph can't cheaply do.

        gt_boxes (B, M, 5) pixel coords; returns
        (box_targets (B, N*4), box_masks (B, N*4), cls_targets (B, N))
        with cls in {0 bg, 1 fg} (plus -1 ignore when mining is on),
        anchor order (Hf, Wf, A) matching the rpn head layout helpers
        below."""
        import jax.numpy as jnp
        from ... import ndarray as nd
        from ...ndarray.ndarray import NDArray
        from ...ops.contrib import _rpn_anchors

        ih, iw = float(im_hw[0]), float(im_hw[1])
        anchors = _rpn_anchors(int(feat_hw[0]), int(feat_hw[1]),
                               self._stride, self._scales, self._ratios)
        norm = anchors / jnp.asarray([iw, ih, iw, ih], jnp.float32)
        gt = gt_boxes.data if isinstance(gt_boxes, NDArray) \
            else jnp.asarray(gt_boxes)
        cls = jnp.where(gt[:, :, :1] >= 0, 0.0, -1.0)  # class-agnostic fg
        boxes = gt[:, :, 1:5] / jnp.asarray([iw, ih, iw, ih], jnp.float32)
        labels = jnp.concatenate([cls, boxes], axis=-1)
        B, N = gt.shape[0], anchors.shape[0]
        if cls_preds is None:
            # zero preds: mining then picks arbitrary (equal-score)
            # negatives; pass the live rpn logits in (B, 2, N) layout for
            # true hard-negative mining
            cls_preds = NDArray(jnp.zeros((B, 2, N), jnp.float32))
        # variances=1: the Proposal op decodes rpn deltas WITHOUT stds
        # (reference RPN convention), so targets must be encoded the same
        return nd.MultiBoxTarget(
            NDArray(norm[None]), NDArray(labels), cls_preds,
            negative_mining_ratio=float(negative_mining_ratio),
            variances=(1.0, 1.0, 1.0, 1.0),
        )

    def rpn_per_anchor(self, rpn_cls, rpn_box):
        """Reshape raw RPN head maps to per-anchor layout matching
        ``rpn_dense_targets``: (B, N, 2) logits and (B, N*4) deltas."""
        B = rpn_cls.shape[0]
        A = self._num_anchors
        Hf, Wf = rpn_cls.shape[2], rpn_cls.shape[3]
        logits = rpn_cls.reshape(B, 2, A, Hf, Wf).transpose(
            0, 3, 4, 2, 1).reshape(B, -1, 2)
        deltas = rpn_box.reshape(B, A, 4, Hf, Wf).transpose(
            0, 3, 4, 1, 2).reshape(B, -1)
        return logits, deltas

    # ------------------------------------------------------------ detect
    def detect(self, x, threshold=0.05, nms_threshold=0.45, topk=20):
        """Inference: (B, K, 6) rows [cls_id, score, x1, y1, x2, y2]
        (pixel coords), NMS'd per class via box_nms."""
        from ... import ndarray as nd

        rois, cls_pred, box_pred = self(x)
        probs = nd.softmax(cls_pred, axis=-1)
        import jax.numpy as jnp
        from ...ndarray.ndarray import NDArray
        from ...ops.contrib import _rcnn_decode, box_nms as _nms

        stds = jnp.asarray([0.1, 0.1, 0.2, 0.2], jnp.float32)
        boxes = _rcnn_decode(rois.data[:, :, 1:5],
                             box_pred.data * stds)  # (B, K, 4)
        p = probs.data[:, :, 1:]  # drop background
        best = jnp.argmax(p, axis=-1)
        score = jnp.max(p, axis=-1)
        score = jnp.where(score > threshold, score, -1.0)
        dets = jnp.concatenate([
            best[..., None].astype(jnp.float32), score[..., None], boxes,
        ], axis=-1)
        out = _nms(dets, overlap_thresh=nms_threshold, topk=topk,
                   coord_start=2, score_index=1, id_index=0)
        return NDArray(out)


def faster_rcnn_tiny(num_classes=2, **kwargs):
    """Small Faster R-CNN for tests/examples (stride-4 backbone)."""
    return FasterRCNN(num_classes, **kwargs)
