"""SSD single-shot detector (reference workload: GluonCV SSD over the
``MultiBoxPrior/Target/Detection`` contrib ops,
``src/operator/contrib/multibox_*.cc`` [unverified]; BASELINE config 5's
model family).

TPU-first shape discipline: every stage emits a static number of anchors,
targets are dense (N anchors, no dynamic gather), and detection ends in
the mask-based ``box_nms`` — the whole train step stages into one XLA
program under ``hybridize()``/TrainStep.
"""

from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock
from ..nn import Activation, BatchNorm, Conv2D, HybridSequential, MaxPool2D

__all__ = ["SSD", "ssd_tiny", "SSDTargetGenerator"]


def _down_block(channels):
    blk = HybridSequential()
    for _ in range(2):
        blk.add(Conv2D(channels, kernel_size=3, padding=1),
                BatchNorm(in_channels=channels),
                Activation("relu"))
    blk.add(MaxPool2D(pool_size=2, strides=2))
    return blk


class _ClassBoxHeads(HybridBlock):
    """Per-scale 3x3 conv heads for class scores and box offsets."""

    def __init__(self, num_anchors, num_classes, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.cls = Conv2D(num_anchors * (num_classes + 1),
                              kernel_size=3, padding=1, prefix="cls_")
            self.box = Conv2D(num_anchors * 4, kernel_size=3, padding=1,
                              prefix="box_")

    def hybrid_forward(self, F, x):
        return self.cls(x), self.box(x)


class SSD(HybridBlock):
    """Configurable SSD.

    forward(images (B, 3, S, S)) ->
        anchors (1, N, 4), cls_preds (B, N, num_classes+1),
        box_preds (B, N*4)
    """

    def __init__(self, num_classes, channels=(16, 32, 64),
                 sizes=((0.2, 0.272), (0.37, 0.447), (0.54, 0.619)),
                 ratios=((1, 2, 0.5),) * 3, **kwargs):
        super().__init__(**kwargs)
        if not (len(channels) == len(sizes) == len(ratios)):
            raise MXNetError("channels/sizes/ratios length mismatch")
        self._num_classes = num_classes
        self._sizes = tuple(tuple(s) for s in sizes)
        self._ratios = tuple(tuple(r) for r in ratios)
        with self.name_scope():
            self.stages = HybridSequential(prefix="stages_")
            for c in channels:
                self.stages.add(_down_block(c))
            self.heads = HybridSequential(prefix="heads_")
            for i in range(len(channels)):
                na = len(self._sizes[i]) + len(self._ratios[i]) - 1
                self.heads.add(_ClassBoxHeads(na, num_classes))

    def hybrid_forward(self, F, x):
        anchors, cls_list, box_list = [], [], []
        for stage, head in zip(self.stages, self.heads):
            x = stage(x)
            i = len(anchors)
            anchors.append(F.MultiBoxPrior(
                x, sizes=self._sizes[i], ratios=self._ratios[i]
            ))
            c, b = head(x)
            # (B, A*(C+1), H, W) -> (B, H*W*A, C+1)
            B = c.shape[0]
            cls_list.append(
                c.transpose(0, 2, 3, 1).reshape(B, -1, self._num_classes + 1)
            )
            box_list.append(b.transpose(0, 2, 3, 1).reshape(B, -1))
        anchors_all = F.concat(*anchors, dim=1)
        cls_all = F.concat(*cls_list, dim=1)
        box_all = F.concat(*box_list, dim=1)
        return anchors_all, cls_all, box_all

    # --------------------------------------------------------------- detect
    def detect(self, x, threshold=0.01, nms_threshold=0.45, nms_topk=100):
        """Inference: (B, N, 6) rows [cls_id, score, corner box]."""
        from ... import ndarray as nd

        anchors, cls_preds, box_preds = self(x)
        probs = nd.softmax(cls_preds, axis=-1).transpose(0, 2, 1)
        return nd.MultiBoxDetection(
            probs, box_preds, anchors, threshold=threshold,
            nms_threshold=nms_threshold, nms_topk=nms_topk,
        )


class SSDTargetGenerator:
    """Training-target helper pairing the net with MultiBoxTarget
    (reference training loop composition)."""

    def __init__(self, overlap_threshold=0.5):
        self._thresh = overlap_threshold

    def __call__(self, anchors, labels, cls_preds):
        from ... import ndarray as nd

        return nd.MultiBoxTarget(
            anchors, labels, cls_preds.transpose(0, 2, 1),
            overlap_threshold=self._thresh,
        )


def ssd_tiny(num_classes=2, **kwargs):
    """Small SSD for tests/examples (three 2x-downsampling stages)."""
    return SSD(num_classes, **kwargs)
