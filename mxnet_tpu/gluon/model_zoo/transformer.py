"""Transformer encoder-decoder for seq2seq (reference workload: GluonNLP
Transformer WMT En-De over contrib interleaved encdec attention ops
[unverified]; BASELINE.md config 4).

Pre-LN arrangement (more stable; graph fusion identical), flash attention
everywhere: causal self-attention in the decoder, cross-attention over
encoder memory.

Inference: every decoder level also speaks the INCREMENTAL protocol
(``prefill``/``decode_step`` with a preallocated ``(max_len, B, H, D)``
KV cache per layer, written via ``lax.dynamic_update_slice``), so one
jitted step emits a token at O(1) cost instead of the O(T²) full
re-forward. ``parallel.infer.InferStep`` drives it; ``model.generate``
is the convenience wrapper. A custom ``encoder=`` block (e.g.
``bert.BERTEncoderForGeneration``) swaps the memory encoder — the
"BERT-as-encoder" prefill configuration."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from ..block import HybridBlock
from ..nn import (
    Dense, Dropout, Embedding, HybridSequential, LayerNorm,
    MultiHeadAttention,
)

__all__ = ["TransformerEncoder", "TransformerDecoder", "TransformerModel",
           "transformer_base", "transformer_big"]


class _FFN(HybridBlock):
    def __init__(self, units, hidden_size, dropout, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ffn_1 = Dense(hidden_size, activation="relu", flatten=False)
            self.ffn_2 = Dense(units, flatten=False)
            self.drop = Dropout(dropout)

    def hybrid_forward(self, F, x):
        return self.drop(self.ffn_2(self.ffn_1(x)))


class TransformerEncoderLayer(HybridBlock):
    _remat_unit = True  # hybridize(remat=...): one checkpoint region/layer

    def __init__(self, units, hidden_size, num_heads, dropout, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ln1 = LayerNorm(in_channels=units)
            self.attn = MultiHeadAttention(units, num_heads, dropout=dropout)
            self.ln2 = LayerNorm(in_channels=units)
            self.ffn = _FFN(units, hidden_size, dropout)
            self.drop = Dropout(dropout)

    def hybrid_forward(self, F, x, valid_length=None):
        # tags feed the names-based remat policy (remat='names:attn_out,
        # ffn_out' keeps exactly these resident); identity otherwise
        x = x + self.drop(F.checkpoint_name(
            self.attn(self.ln1(x), valid_length=valid_length),
            name="attn_out"))
        return x + F.checkpoint_name(self.ffn(self.ln2(x)), name="ffn_out")


class TransformerDecoderLayer(HybridBlock):
    _remat_unit = True

    def __init__(self, units, hidden_size, num_heads, dropout, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ln1 = LayerNorm(in_channels=units)
            self.self_attn = MultiHeadAttention(
                units, num_heads, dropout=dropout, causal=True
            )
            self.ln2 = LayerNorm(in_channels=units)
            self.cross_attn = MultiHeadAttention(
                units, num_heads, dropout=dropout, self_attention=False
            )
            self.ln3 = LayerNorm(in_channels=units)
            self.ffn = _FFN(units, hidden_size, dropout)
            self.drop = Dropout(dropout)

    def hybrid_forward(self, F, x, memory, mem_valid_length=None):
        x = x + self.drop(F.checkpoint_name(self.self_attn(self.ln1(x)),
                                            name="attn_out"))
        x = x + self.drop(F.checkpoint_name(
            self.cross_attn(self.ln2(x), memory, memory,
                            valid_length=mem_valid_length),
            name="attn_out"))
        return x + F.checkpoint_name(self.ffn(self.ln3(x)), name="ffn_out")

    # ----------------------------------------------------- incremental mode
    def prefill(self, x, memory, mem_valid_length=None):
        """Full-prefix forward that seeds the decode state: returns
        ``(y, (k_self, v_self), (k_mem, v_mem))`` — the layer output
        (bit-matching ``__call__``), the causal prefix K/V ``(B, Lp, H,
        D)``, and the memory projections reused by every decode step."""
        a, k_s, v_s = self.self_attn.prefill(self.ln1(x))
        x = x + self.drop(a)
        k_m, v_m = self.cross_attn.project_kv(memory)
        c = self.cross_attn.attend(self.ln2(x), k_m, v_m,
                                   valid_length=mem_valid_length)
        x = x + self.drop(c)
        y = x + self.ffn(self.ln3(x))
        return y, (k_s, v_s), (k_m, v_m)

    def step(self, x, self_kv, pos, cross_kv, mem_valid_length=None):
        """One incremental token: ``x`` (B, 1, units), ``self_kv`` the
        raw ``(max_len, B, H, D)`` cache pair (updated in place via
        dynamic_update_slice and returned), ``pos`` the traced cache
        offset, ``cross_kv`` the static memory projections."""
        a, k_c, v_c = self.self_attn.step(
            self.ln1(x), self_kv[0], self_kv[1], pos)
        x = x + self.drop(a)
        c = self.cross_attn.attend(self.ln2(x), cross_kv[0], cross_kv[1],
                                   valid_length=mem_valid_length)
        x = x + self.drop(c)
        y = x + self.ffn(self.ln3(x))
        return y, (k_c, v_c)

    def step_paged(self, x, k_pool, v_pool, page_table, pos, active,
                   cross_kv, mem_valid_length=None):
        """``step`` over the paged KV pool: per-row ``pos`` (B,) cache
        lengths instead of one shared scalar offset — the continuous-
        batching contract where every slot sits at its own depth."""
        a, k_pool, v_pool = self.self_attn.paged_step(
            self.ln1(x), k_pool, v_pool, page_table, pos, active)
        x = x + self.drop(a)
        c = self.cross_attn.attend(self.ln2(x), cross_kv[0], cross_kv[1],
                                   valid_length=mem_valid_length)
        x = x + self.drop(c)
        y = x + self.ffn(self.ln3(x))
        return y, k_pool, v_pool

    def step_window_paged(self, x, k_pool, v_pool, page_table, pos, active,
                          cross_kv, mem_valid_length=None, window_vl=None):
        """``step_paged`` widened to an S-token window: ``x`` (B, S,
        units) sits at per-row absolute positions ``pos[b] + i``, all S
        tokens scatter and attend in ONE pass (speculative verification
        and wide suffix replay). ``window_vl`` marks per-row padding
        tails inside the window."""
        a, k_pool, v_pool = self.self_attn.paged_window_step(
            self.ln1(x), k_pool, v_pool, page_table, pos, active,
            window_vl=window_vl)
        x = x + self.drop(a)
        c = self.cross_attn.attend(self.ln2(x), cross_kv[0], cross_kv[1],
                                   valid_length=mem_valid_length)
        x = x + self.drop(c)
        y = x + self.ffn(self.ln3(x))
        return y, k_pool, v_pool


class TransformerEncoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads, dropout,
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.layers = HybridSequential()
            for _ in range(num_layers):
                self.layers.add(
                    TransformerEncoderLayer(units, hidden_size, num_heads,
                                            dropout)
                )
            self.ln = LayerNorm(in_channels=units)

    def hybrid_forward(self, F, x, valid_length=None):
        for layer in self.layers:
            x = layer(x, valid_length=valid_length)
        return self.ln(x)


class TransformerDecoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads, dropout,
                 **kwargs):
        super().__init__(**kwargs)
        self._n = num_layers
        with self.name_scope():
            for i in range(num_layers):
                setattr(self, f"layer{i}",
                        TransformerDecoderLayer(units, hidden_size, num_heads,
                                                dropout))
            self.ln = LayerNorm(in_channels=units)

    def hybrid_forward(self, F, x, memory, mem_valid_length=None):
        for i in range(self._n):
            x = getattr(self, f"layer{i}")(x, memory,
                                           mem_valid_length=mem_valid_length)
        return self.ln(x)


class TransformerModel(HybridBlock):
    """forward(src_ids, tgt_ids[, src_valid_length]) -> logits
    (B, T_tgt, vocab). ``src_valid_length`` (B,) masks source padding out
    of encoder self-attention AND decoder cross-attention — the bucketed
    (pad-to-menu) prefill contract.

    ``encoder``: optional custom memory encoder block with call signature
    ``encoder(src_ids, valid_length) -> (B, S, units)`` replacing the
    built-in embedding + TransformerEncoder stack (its output width must
    equal ``units``) — e.g. ``bert.BERTEncoderForGeneration``."""

    def __init__(self, src_vocab=32768, tgt_vocab=32768, units=512,
                 hidden_size=2048, num_layers=6, num_heads=8, max_length=1024,
                 dropout=0.1, tie_weights=True, encoder=None, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._custom_encoder = encoder is not None
        with self.name_scope():
            if not self._custom_encoder:
                self.src_embed = Embedding(src_vocab, units,
                                           prefix="src_embed_")
            self.tgt_embed = Embedding(tgt_vocab, units, prefix="tgt_embed_")
            self.pos_embed = Embedding(max_length, units, prefix="pos_embed_")
            self.drop = Dropout(dropout)
            if self._custom_encoder:
                self.encoder = encoder
            else:
                self.encoder = TransformerEncoder(
                    num_layers, units, hidden_size, num_heads, dropout,
                    prefix="enc_",
                )
            self.decoder = TransformerDecoder(
                num_layers, units, hidden_size, num_heads, dropout,
                prefix="dec_",
            )
            self._tied = tie_weights
            if not tie_weights:
                self.proj = Dense(tgt_vocab, flatten=False, prefix="proj_")

    def _embed(self, F, embed, ids):
        B, S = ids.shape[0], ids.shape[1]
        pos = F.arange(0, S).reshape(1, S).broadcast_to((B, S))
        return self.drop(embed(ids) * (self._units ** 0.5)
                         + self.pos_embed(pos))

    def _logits(self, F, out):
        if self._tied:
            w = self.tgt_embed.weight.data()
            return F.dot(out, w.T)
        return self.proj(out)

    def encode(self, src_ids, valid_length=None):
        """Source ids -> (B, S, units) memory (the prefill encoder half;
        padding past ``valid_length`` is masked out of attention)."""
        from ... import ndarray as F

        if self._custom_encoder:
            out = self.encoder(src_ids, valid_length)
            return out[0] if isinstance(out, tuple) else out
        return self.encoder(self._embed(F, self.src_embed, src_ids),
                            valid_length=valid_length)

    def hybrid_forward(self, F, src_ids, tgt_ids, src_valid_length=None):
        memory = self.encode(src_ids, src_valid_length)
        out = self.decoder(self._embed(F, self.tgt_embed, tgt_ids), memory,
                           mem_valid_length=src_valid_length)
        return self._logits(F, out)

    # ----------------------------------------------------- incremental mode
    def prefill(self, src_ids, tgt_prefix, src_valid_length=None,
                max_len=64, cache_dtype=None):
        """Encode the source and run the target prefix ONCE, seeding the
        per-layer KV caches.

        Returns ``(last_logits, state)``: ``last_logits`` (B, vocab) are
        the logits predicting the token AFTER the prefix (bit-matching
        column ``Lp-1`` of the full forward), ``state`` is the decode
        pytree — per-layer ``(max_len, B, H, D)`` self-attention cache
        pairs (prefix written at rows ``[0, Lp)``), static cross-attention
        memory projections, and the source mask."""
        logits, self_parts, cross_parts, vl_raw = self.prefill_parts(
            src_ids, tgt_prefix, src_valid_length)
        B = tgt_prefix.shape[0]
        self_kv, cross_kv = [], []
        for i in range(self.decoder._n):
            layer = getattr(self.decoder, f"layer{i}")
            k_s, v_s = self_parts[i]
            kc, vc = layer.self_attn.init_cache(
                B, max_len, cache_dtype or k_s.dtype)
            zero = (0, 0, 0, 0)
            kc = jax.lax.dynamic_update_slice(kc, jnp.swapaxes(k_s, 0, 1),
                                              zero)
            vc = jax.lax.dynamic_update_slice(vc, jnp.swapaxes(v_s, 0, 1),
                                              zero)
            self_kv.append((kc, vc))
            cross_kv.append(cross_parts[i])
        state = {"self_kv": tuple(self_kv), "cross_kv": tuple(cross_kv),
                 "mem_vl": vl_raw}
        return logits, state

    def prefill_parts(self, src_ids, tgt_prefix, src_valid_length=None):
        """The prefill compute WITHOUT a cache layout: encode the source,
        run the target prefix, and return the raw per-layer pieces —
        ``(last_logits, [(k_s, v_s)], [(k_m, v_m)], mem_vl)`` with the
        prefix K/V as ``(B, Lp, H, D)`` arrays. ``prefill`` packs them
        into dense ``(max_len, B, H, D)`` caches; the paged engine
        scatters them into pool pages instead — both consume the exact
        same forward, so the two layouts start from identical state."""
        from ... import ndarray as F

        memory = self.encode(src_ids, src_valid_length)
        x = self._embed(F, self.tgt_embed, tgt_prefix)
        vl_raw = None if src_valid_length is None else (
            src_valid_length.data if isinstance(src_valid_length, NDArray)
            else jnp.asarray(src_valid_length))
        self_parts, cross_parts = [], []
        for i in range(self.decoder._n):
            layer = getattr(self.decoder, f"layer{i}")
            x, (k_s, v_s), (k_m, v_m) = layer.prefill(
                x, memory, mem_valid_length=src_valid_length)
            self_parts.append((k_s, v_s))
            cross_parts.append((k_m, v_m))
        out = self.decoder.ln(x)
        logits = self._logits(F, out[:, -1:, :])[:, 0]
        return logits, self_parts, cross_parts, vl_raw

    def decode_step(self, tokens, pos, state):
        """One O(1) incremental decode step: place ``tokens`` (B,) int32
        at absolute target position ``pos`` (a traced scalar; the number
        of tokens already cached) and return ``(logits, new_state)`` —
        ``logits`` (B, vocab) predict position ``pos + 1``'s token and
        bit-match column ``pos`` of a full re-forward."""
        from ... import ndarray as F

        x = self._embed_step(tokens, pos)
        mem_vl = state["mem_vl"]
        mem_vl_nd = None if mem_vl is None else NDArray(mem_vl)
        new_self = []
        for i in range(self.decoder._n):
            layer = getattr(self.decoder, f"layer{i}")
            x, kv = layer.step(x, state["self_kv"][i], pos,
                               state["cross_kv"][i],
                               mem_valid_length=mem_vl_nd)
            new_self.append(kv)
        out = self.decoder.ln(x)
        logits = self._logits(F, out)[:, 0]
        return logits, {"self_kv": tuple(new_self),
                        "cross_kv": state["cross_kv"], "mem_vl": mem_vl}

    def _embed_step(self, tokens, pos):
        """Single-position target embedding (token + absolute position).
        ``pos`` is a scalar (every row at the same depth — the dense
        decode loop) or a per-row (B,) vector (paged continuous batching,
        where each slot sits at its own depth)."""
        tok = tokens.data if isinstance(tokens, NDArray) else \
            jnp.asarray(tokens)
        B = tok.shape[0]
        ids = NDArray(tok.reshape(B, 1).astype(jnp.int32))
        pos_ids = NDArray(jnp.broadcast_to(
            jnp.asarray(pos, jnp.int32).reshape(-1, 1), (B, 1)))
        return self.drop(self.tgt_embed(ids) * (self._units ** 0.5)
                         + self.pos_embed(pos_ids))

    # -------------------------------------------------------- paged decode
    # The paged protocol (continuous batching, ISSUE 8): K/V live in shared
    # per-layer (num_pages, page_size, H, D) pools with per-slot page
    # tables; cross-attention memory sits in per-slot (slots, mem_len, H,
    # D) buffers written once at admission. The batch dimension is the
    # SLOT menu — static shape, dynamic occupancy.

    def init_paged_state(self, slots, num_pages, page_size, mem_len,
                         dtype=None):
        """Allocate the paged decode state: per-decoder-layer K/V pools,
        per-slot cross-attention memory buffers, and the per-slot source
        valid lengths. ``state['page_tables']`` starts all-trash (page 0);
        the serving-side ``PagePool`` owns the real table."""
        k_pools, v_pools, cross_k, cross_v = [], [], [], []
        for i in range(self.decoder._n):
            layer = getattr(self.decoder, f"layer{i}")
            kp, vp = layer.self_attn.init_page_pool(num_pages, page_size,
                                                    dtype)
            k_pools.append(kp)
            v_pools.append(vp)
            H = layer.cross_attn._num_heads
            D = layer.cross_attn._head_dim
            dt = dtype if dtype is not None \
                else layer.cross_attn.out_proj.weight.dtype
            z = jnp.zeros((int(slots), int(mem_len), H, D), jnp.dtype(dt))
            cross_k.append(z)
            cross_v.append(z)
        return {
            "k_pools": tuple(k_pools), "v_pools": tuple(v_pools),
            "cross_k": tuple(cross_k), "cross_v": tuple(cross_v),
            "mem_vl": jnp.zeros((int(slots),), jnp.int32),
        }

    def prefill_paged(self, src_ids, tgt_prime, src_valid_length, state,
                      slot_ids, first_pages, active):
        """Admission prefill INTO pages: run the identical prefill forward
        (``prefill_parts``) over a padded admission batch, then scatter
        row ``r``'s prefix K/V into page ``first_pages[r]`` and its memory
        projections into slot ``slot_ids[r]``'s cross buffers.

        Rows with ``active[r]`` False are padding: their page writes land
        in the trash page 0 and their slot writes carry an out-of-bounds
        ``slot_ids[r]`` (= slots), which jax scatter semantics DROP — so
        one fixed ``(slots, bucket)`` admission shape serves any number of
        admitted requests without touching live slots. Returns
        ``(last_logits, new_state)``; the single-column prime (BOS) lands
        at logical position 0, so the admitted row starts with cache
        length 1."""
        if tgt_prime.shape[1] != 1:
            raise MXNetError(
                "prefill_paged primes with a single BOS column; explicit "
                "prefixes decode through the dense engine path")
        logits, self_parts, cross_parts, vl_raw = self.prefill_parts(
            src_ids, tgt_prime, src_valid_length)
        first_pages = jnp.where(active, jnp.asarray(first_pages, jnp.int32),
                                0)
        mem_len = state["cross_k"][0].shape[1]
        k_pools, v_pools, cross_k, cross_v = [], [], [], []
        for i in range(self.decoder._n):
            k_s, v_s = self_parts[i]
            kp = state["k_pools"][i].at[first_pages, 0].set(
                k_s[:, 0].astype(state["k_pools"][i].dtype))
            vp = state["v_pools"][i].at[first_pages, 0].set(
                v_s[:, 0].astype(state["v_pools"][i].dtype))
            k_pools.append(kp)
            v_pools.append(vp)
            k_m, v_m = cross_parts[i]
            pad = mem_len - k_m.shape[1]
            if pad:
                widths = ((0, 0), (0, pad), (0, 0), (0, 0))
                k_m = jnp.pad(k_m, widths)
                v_m = jnp.pad(v_m, widths)
            dt = state["cross_k"][i].dtype
            cross_k.append(state["cross_k"][i].at[slot_ids].set(
                k_m.astype(dt)))
            cross_v.append(state["cross_v"][i].at[slot_ids].set(
                v_m.astype(dt)))
        vl = vl_raw if vl_raw is not None else jnp.full(
            (src_ids.shape[0],), src_ids.shape[1], jnp.int32)
        mem_vl = state["mem_vl"].at[slot_ids].set(vl.astype(jnp.int32))
        new_state = {"k_pools": tuple(k_pools), "v_pools": tuple(v_pools),
                     "cross_k": tuple(cross_k), "cross_v": tuple(cross_v),
                     "mem_vl": mem_vl}
        return logits, new_state

    def prefill_suffix_paged(self, tokens, token_vl, q_offset, state,
                             page_tables, slot_ids, active, wide=False):
        """Prefix-cache suffix prefill: decode-side forward over ONLY the
        uncached tail of each admitted row's target prefix, at absolute
        positions ``q_offset[r] + j``.

        ``tokens`` (B, S) int32 are the left-aligned suffix token ids
        (``token_vl`` (B,) of them real per row); ``page_tables`` (B, P)
        are the admitted rows' page-table rows (padding rows all-trash);
        ``slot_ids`` (B,) map rows to slots for the cross-memory gather —
        the slot's cross buffers and ``mem_vl`` must already be populated
        (by a prior ``prefill_paged``, an adopted cache root, or a disagg
        handoff; this method deliberately runs NO encoder — skipping it
        is the point of a prefix hit). Padding rows carry out-of-bounds
        ``slot_ids`` whose gathers clamp harmlessly and whose page writes
        land in trash.

        Bit-identity contract: each position runs through the exact
        ``decode_step_paged`` program (a teacher-forced ``fori_loop``,
        one position per step) rather than one batched multi-token
        attention — a wide-S pass computes the same math but rounds
        differently per shape, so cached pages would drift from the
        token-at-a-time stream in the last float bits. Per-step bodies
        are shape-identical no matter where the cached/uncached split
        falls, which is what makes a cache-hit replay bit-identical to
        the cold path (asserted in tests/test_prefix.py). ``wide=True``
        opts out of that contract for speed: the whole suffix runs as
        ONE ``decode_window_paged`` pass — the q_offset-aware shape the
        Pallas paged window kernel accelerates — computing the same
        masked-softmax math with wide-shape rounding (equal argmax in
        practice, not bit-exact). Returns ``(last_logits, new_state)``
        with row ``r``'s logits taken at suffix position
        ``token_vl[r] - 1`` — the first new token's."""
        import jax

        tok = tokens.data if isinstance(tokens, NDArray) else \
            jnp.asarray(tokens)
        tok = tok.astype(jnp.int32)
        S = tok.shape[1]
        q_offset = jnp.asarray(q_offset, jnp.int32)
        token_vl = jnp.asarray(token_vl, jnp.int32)
        active = jnp.asarray(active, jnp.bool_)
        # per-row cross memory gathered by slot once; empty slots report
        # mem_vl 0 — clamp so padding rows' masked softmax stays finite
        # (their output is discarded anyway)
        sub = {"k_pools": state["k_pools"], "v_pools": state["v_pools"],
               "cross_k": tuple(c[slot_ids] for c in state["cross_k"]),
               "cross_v": tuple(c[slot_ids] for c in state["cross_v"]),
               "mem_vl": jnp.maximum(state["mem_vl"][slot_ids], 1)}

        if wide:
            logits, sub = self.decode_window_paged(
                NDArray(tok), q_offset, sub, page_tables, active,
                window_vl=token_vl)
            lg = logits.data if isinstance(logits, NDArray) else logits
            idx = jnp.clip(token_vl - 1, 0, S - 1).astype(jnp.int32)
            last = jnp.take_along_axis(lg, idx[:, None, None], axis=1)[:, 0]
            new_state = dict(state)
            new_state["k_pools"] = sub["k_pools"]
            new_state["v_pools"] = sub["v_pools"]
            return last, new_state

        def one(j, sub):
            tok_j = jax.lax.dynamic_index_in_dim(tok, j, axis=1,
                                                 keepdims=False)
            live = jnp.logical_and(active, j < token_vl)
            lg, sub = self.decode_step_paged(
                NDArray(tok_j), q_offset + j, sub, page_tables, live)
            return (lg.data if isinstance(lg, NDArray) else lg), sub

        last, sub = one(0, sub)

        def body(j, carry):
            sub, last = carry
            lg, sub = one(j, sub)
            return sub, jnp.where((j == token_vl - 1)[:, None], lg, last)

        if S > 1:
            sub, last = jax.lax.fori_loop(1, S, body, (sub, last))
        new_state = dict(state)
        new_state["k_pools"] = sub["k_pools"]
        new_state["v_pools"] = sub["v_pools"]
        return last, new_state

    def decode_window_paged(self, tokens, pos, state, page_tables, active,
                            window_vl=None):
        """An S-token window through the paged cache in ONE forward:
        ``tokens`` (slots, S) int32 at per-row absolute positions
        ``pos[r] + j``. This is the speculative-verification shape — one
        dispatch scores a drafted window against the target model — and
        the wide (non-bit-exact) suffix-replay shape. ``window_vl``
        (slots,) marks tokens ``>= window_vl[r]`` as padding (their K/V
        land in trash, their logits are garbage). Returns ``(logits
        (slots, S, vocab), new_state)``; column ``j`` predicts position
        ``pos[r] + j + 1``'s token, matching ``decode_step_paged`` run
        sequentially up to attention-order float rounding."""
        from ... import ndarray as F

        tok = tokens.data if isinstance(tokens, NDArray) else \
            jnp.asarray(tokens)
        tok = tok.astype(jnp.int32)
        B, S = tok.shape
        pos = jnp.asarray(pos, jnp.int32)
        pos_ids = pos[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
        x = self.drop(self.tgt_embed(NDArray(tok)) * (self._units ** 0.5)
                      + self.pos_embed(NDArray(pos_ids)))
        mem_vl_nd = NDArray(state["mem_vl"])
        k_pools, v_pools = [], []
        for i in range(self.decoder._n):
            layer = getattr(self.decoder, f"layer{i}")
            x, kp, vp = layer.step_window_paged(
                x, state["k_pools"][i], state["v_pools"][i], page_tables,
                pos, active, (state["cross_k"][i], state["cross_v"][i]),
                mem_valid_length=mem_vl_nd, window_vl=window_vl)
            k_pools.append(kp)
            v_pools.append(vp)
        out = self.decoder.ln(x)
        logits = self._logits(F, out)
        new_state = dict(state)
        new_state["k_pools"] = tuple(k_pools)
        new_state["v_pools"] = tuple(v_pools)
        return logits, new_state

    def decode_step_paged(self, tokens, pos, state, page_tables, active):
        """One O(1) paged decode step over the SLOT batch: ``tokens``
        (slots,) int32 at per-row absolute positions ``pos`` (slots,),
        gathered/scattered through ``page_tables`` (slots, P). Rows with
        ``active`` False write to the trash page and their logits are
        garbage (the scheduler discards them). Returns ``(logits,
        new_state)`` with the updated pools."""
        from ... import ndarray as F

        x = self._embed_step(tokens, pos)
        mem_vl_nd = NDArray(state["mem_vl"])
        k_pools, v_pools = [], []
        for i in range(self.decoder._n):
            layer = getattr(self.decoder, f"layer{i}")
            x, kp, vp = layer.step_paged(
                x, state["k_pools"][i], state["v_pools"][i], page_tables,
                pos, active, (state["cross_k"][i], state["cross_v"][i]),
                mem_valid_length=mem_vl_nd)
            k_pools.append(kp)
            v_pools.append(vp)
        out = self.decoder.ln(x)
        logits = self._logits(F, out)[:, 0]
        new_state = dict(state)
        new_state["k_pools"] = tuple(k_pools)
        new_state["v_pools"] = tuple(v_pools)
        return logits, new_state

    def generate(self, src_ids, src_valid_length=None, max_new_tokens=32,
                 **kwargs):
        """KV-cached generation through a lazily-built (and cached)
        ``parallel.infer.InferStep``. Engine kwargs (``amp``, ``max_len``,
        ``bos_id``/``eos_id``/``pad_id``) configure the cached engine;
        the rest (``method``, ``top_k``, ``temperature``, ``seed``) pass
        through.

        Greedy calls route through a cached ``serving.ContinuousBatcher``
        (iteration-level scheduling over the paged KV pool — rows that hit
        EOS free their slot and pages immediately) unless
        ``MXTPU_BATCHER=fixed`` (the PR-5 fixed-dispatch ``decode_n``
        path). Sampling with an explicit ``seed`` keeps the direct path:
        its key schedule is per-dispatch and only reproducible there.
        Returns ``(tokens, lengths)`` NDArrays either way."""
        from ...parallel.infer import InferStep

        eng_keys = ("amp", "max_len", "bos_id", "eos_id", "pad_id")
        eng_kw = {k: kwargs.pop(k) for k in eng_keys if k in kwargs}
        cache_key = tuple(sorted(eng_kw.items()))
        steps = getattr(self, "_infer_steps", None)
        if steps is None:
            steps = {}
            object.__setattr__(self, "_infer_steps", steps)
        if cache_key not in steps:
            steps[cache_key] = InferStep(self, **eng_kw)
        engine = steps[cache_key]
        if self._use_batcher_path(engine, kwargs):
            return self._generate_batched(engine, cache_key, src_ids,
                                          src_valid_length,
                                          max_new_tokens, **kwargs)
        return engine.generate(
            src_ids, src_valid_length, max_new_tokens=max_new_tokens,
            **kwargs)

    @staticmethod
    def _use_batcher_path(engine, kwargs) -> bool:
        from ...serving.batcher import batcher_kind

        if batcher_kind() in ("fixed", "off", "direct"):
            return False
        if kwargs.get("method", "greedy") != "greedy" or \
                kwargs.get("seed") is not None:
            return False  # per-dispatch key schedule: direct path only
        return getattr(engine, "supports_paged", False)

    def _generate_batched(self, engine, cache_key, src_ids,
                          src_valid_length, max_new_tokens, **kwargs):
        """One synchronous generate() call as N serving requests through a
        cached ContinuousBatcher: submit every row, gather the trimmed
        token lists back into the ``decode_n``-shaped ``(tokens (B,
        max_new), lengths (B,))`` pair."""
        import numpy as _np

        from ... import ndarray as _nd
        from ...serving.batcher import ContinuousBatcher

        src = src_ids.asnumpy() if hasattr(src_ids, "asnumpy") \
            else _np.asarray(src_ids)
        src = src.astype(_np.int32)
        B, L = src.shape
        if src_valid_length is None:
            vl = _np.full((B,), L, _np.int32)
        else:
            vl = (src_valid_length.asnumpy()
                  if hasattr(src_valid_length, "asnumpy")
                  else _np.asarray(src_valid_length)).astype(_np.int32)
        max_new = int(max_new_tokens)
        batchers = getattr(self, "_batchers", None)
        if batchers is None:
            batchers = {}
            object.__setattr__(self, "_batchers", batchers)
        bk = (cache_key, B, L)
        bat = batchers.get(bk)
        if bat is None or bat.max_new < max_new:
            if bat is not None:
                bat.stop()
            bat = ContinuousBatcher(
                engine, bucket_keys=(L,), slots=min(B, 8),
                max_new_tokens=max(max_new, 8),
                sampling={k: v for k, v in kwargs.items()
                          if k in ("method", "top_k", "temperature")},
                name="generate")
            batchers[bk] = bat
        futs = [bat.submit(src[i, :vl[i]] if vl[i] else src[i, :1],
                           max_new_tokens=max_new) for i in range(B)]
        toks = _np.full((B, max_new), bat._pad, _np.int32)
        lengths = _np.zeros((B,), _np.int32)
        for i, f in enumerate(futs):
            got = f.result(timeout=600)
            n = min(len(got), max_new)
            toks[i, :n] = got[:n]
            lengths[i] = n
        return _nd.array(toks, dtype="int32"), \
            _nd.array(lengths, dtype="int32")


def transformer_base(**kwargs):
    return TransformerModel(units=512, hidden_size=2048, num_layers=6,
                            num_heads=8, **kwargs)


def transformer_big(**kwargs):
    return TransformerModel(units=1024, hidden_size=4096, num_layers=6,
                            num_heads=16, **kwargs)
