"""Transformer encoder-decoder for seq2seq (reference workload: GluonNLP
Transformer WMT En-De over contrib interleaved encdec attention ops
[unverified]; BASELINE.md config 4).

Pre-LN arrangement (more stable; graph fusion identical), flash attention
everywhere: causal self-attention in the decoder, cross-attention over
encoder memory.

Inference: every decoder level also speaks the INCREMENTAL protocol
(``prefill``/``decode_step`` with a preallocated ``(max_len, B, H, D)``
KV cache per layer, written via ``lax.dynamic_update_slice``), so one
jitted step emits a token at O(1) cost instead of the O(T²) full
re-forward. ``parallel.infer.InferStep`` drives it; ``model.generate``
is the convenience wrapper. A custom ``encoder=`` block (e.g.
``bert.BERTEncoderForGeneration``) swaps the memory encoder — the
"BERT-as-encoder" prefill configuration."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ndarray.ndarray import NDArray
from ..block import HybridBlock
from ..nn import (
    Dense, Dropout, Embedding, HybridSequential, LayerNorm,
    MultiHeadAttention,
)

__all__ = ["TransformerEncoder", "TransformerDecoder", "TransformerModel",
           "transformer_base", "transformer_big"]


class _FFN(HybridBlock):
    def __init__(self, units, hidden_size, dropout, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ffn_1 = Dense(hidden_size, activation="relu", flatten=False)
            self.ffn_2 = Dense(units, flatten=False)
            self.drop = Dropout(dropout)

    def hybrid_forward(self, F, x):
        return self.drop(self.ffn_2(self.ffn_1(x)))


class TransformerEncoderLayer(HybridBlock):
    _remat_unit = True  # hybridize(remat=...): one checkpoint region/layer

    def __init__(self, units, hidden_size, num_heads, dropout, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ln1 = LayerNorm(in_channels=units)
            self.attn = MultiHeadAttention(units, num_heads, dropout=dropout)
            self.ln2 = LayerNorm(in_channels=units)
            self.ffn = _FFN(units, hidden_size, dropout)
            self.drop = Dropout(dropout)

    def hybrid_forward(self, F, x, valid_length=None):
        # tags feed the names-based remat policy (remat='names:attn_out,
        # ffn_out' keeps exactly these resident); identity otherwise
        x = x + self.drop(F.checkpoint_name(
            self.attn(self.ln1(x), valid_length=valid_length),
            name="attn_out"))
        return x + F.checkpoint_name(self.ffn(self.ln2(x)), name="ffn_out")


class TransformerDecoderLayer(HybridBlock):
    _remat_unit = True

    def __init__(self, units, hidden_size, num_heads, dropout, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ln1 = LayerNorm(in_channels=units)
            self.self_attn = MultiHeadAttention(
                units, num_heads, dropout=dropout, causal=True
            )
            self.ln2 = LayerNorm(in_channels=units)
            self.cross_attn = MultiHeadAttention(
                units, num_heads, dropout=dropout, self_attention=False
            )
            self.ln3 = LayerNorm(in_channels=units)
            self.ffn = _FFN(units, hidden_size, dropout)
            self.drop = Dropout(dropout)

    def hybrid_forward(self, F, x, memory, mem_valid_length=None):
        x = x + self.drop(F.checkpoint_name(self.self_attn(self.ln1(x)),
                                            name="attn_out"))
        x = x + self.drop(F.checkpoint_name(
            self.cross_attn(self.ln2(x), memory, memory,
                            valid_length=mem_valid_length),
            name="attn_out"))
        return x + F.checkpoint_name(self.ffn(self.ln3(x)), name="ffn_out")

    # ----------------------------------------------------- incremental mode
    def prefill(self, x, memory, mem_valid_length=None):
        """Full-prefix forward that seeds the decode state: returns
        ``(y, (k_self, v_self), (k_mem, v_mem))`` — the layer output
        (bit-matching ``__call__``), the causal prefix K/V ``(B, Lp, H,
        D)``, and the memory projections reused by every decode step."""
        a, k_s, v_s = self.self_attn.prefill(self.ln1(x))
        x = x + self.drop(a)
        k_m, v_m = self.cross_attn.project_kv(memory)
        c = self.cross_attn.attend(self.ln2(x), k_m, v_m,
                                   valid_length=mem_valid_length)
        x = x + self.drop(c)
        y = x + self.ffn(self.ln3(x))
        return y, (k_s, v_s), (k_m, v_m)

    def step(self, x, self_kv, pos, cross_kv, mem_valid_length=None):
        """One incremental token: ``x`` (B, 1, units), ``self_kv`` the
        raw ``(max_len, B, H, D)`` cache pair (updated in place via
        dynamic_update_slice and returned), ``pos`` the traced cache
        offset, ``cross_kv`` the static memory projections."""
        a, k_c, v_c = self.self_attn.step(
            self.ln1(x), self_kv[0], self_kv[1], pos)
        x = x + self.drop(a)
        c = self.cross_attn.attend(self.ln2(x), cross_kv[0], cross_kv[1],
                                   valid_length=mem_valid_length)
        x = x + self.drop(c)
        y = x + self.ffn(self.ln3(x))
        return y, (k_c, v_c)


class TransformerEncoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads, dropout,
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.layers = HybridSequential()
            for _ in range(num_layers):
                self.layers.add(
                    TransformerEncoderLayer(units, hidden_size, num_heads,
                                            dropout)
                )
            self.ln = LayerNorm(in_channels=units)

    def hybrid_forward(self, F, x, valid_length=None):
        for layer in self.layers:
            x = layer(x, valid_length=valid_length)
        return self.ln(x)


class TransformerDecoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads, dropout,
                 **kwargs):
        super().__init__(**kwargs)
        self._n = num_layers
        with self.name_scope():
            for i in range(num_layers):
                setattr(self, f"layer{i}",
                        TransformerDecoderLayer(units, hidden_size, num_heads,
                                                dropout))
            self.ln = LayerNorm(in_channels=units)

    def hybrid_forward(self, F, x, memory, mem_valid_length=None):
        for i in range(self._n):
            x = getattr(self, f"layer{i}")(x, memory,
                                           mem_valid_length=mem_valid_length)
        return self.ln(x)


class TransformerModel(HybridBlock):
    """forward(src_ids, tgt_ids[, src_valid_length]) -> logits
    (B, T_tgt, vocab). ``src_valid_length`` (B,) masks source padding out
    of encoder self-attention AND decoder cross-attention — the bucketed
    (pad-to-menu) prefill contract.

    ``encoder``: optional custom memory encoder block with call signature
    ``encoder(src_ids, valid_length) -> (B, S, units)`` replacing the
    built-in embedding + TransformerEncoder stack (its output width must
    equal ``units``) — e.g. ``bert.BERTEncoderForGeneration``."""

    def __init__(self, src_vocab=32768, tgt_vocab=32768, units=512,
                 hidden_size=2048, num_layers=6, num_heads=8, max_length=1024,
                 dropout=0.1, tie_weights=True, encoder=None, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._custom_encoder = encoder is not None
        with self.name_scope():
            if not self._custom_encoder:
                self.src_embed = Embedding(src_vocab, units,
                                           prefix="src_embed_")
            self.tgt_embed = Embedding(tgt_vocab, units, prefix="tgt_embed_")
            self.pos_embed = Embedding(max_length, units, prefix="pos_embed_")
            self.drop = Dropout(dropout)
            if self._custom_encoder:
                self.encoder = encoder
            else:
                self.encoder = TransformerEncoder(
                    num_layers, units, hidden_size, num_heads, dropout,
                    prefix="enc_",
                )
            self.decoder = TransformerDecoder(
                num_layers, units, hidden_size, num_heads, dropout,
                prefix="dec_",
            )
            self._tied = tie_weights
            if not tie_weights:
                self.proj = Dense(tgt_vocab, flatten=False, prefix="proj_")

    def _embed(self, F, embed, ids):
        B, S = ids.shape[0], ids.shape[1]
        pos = F.arange(0, S).reshape(1, S).broadcast_to((B, S))
        return self.drop(embed(ids) * (self._units ** 0.5)
                         + self.pos_embed(pos))

    def _logits(self, F, out):
        if self._tied:
            w = self.tgt_embed.weight.data()
            return F.dot(out, w.T)
        return self.proj(out)

    def encode(self, src_ids, valid_length=None):
        """Source ids -> (B, S, units) memory (the prefill encoder half;
        padding past ``valid_length`` is masked out of attention)."""
        from ... import ndarray as F

        if self._custom_encoder:
            out = self.encoder(src_ids, valid_length)
            return out[0] if isinstance(out, tuple) else out
        return self.encoder(self._embed(F, self.src_embed, src_ids),
                            valid_length=valid_length)

    def hybrid_forward(self, F, src_ids, tgt_ids, src_valid_length=None):
        memory = self.encode(src_ids, src_valid_length)
        out = self.decoder(self._embed(F, self.tgt_embed, tgt_ids), memory,
                           mem_valid_length=src_valid_length)
        return self._logits(F, out)

    # ----------------------------------------------------- incremental mode
    def prefill(self, src_ids, tgt_prefix, src_valid_length=None,
                max_len=64, cache_dtype=None):
        """Encode the source and run the target prefix ONCE, seeding the
        per-layer KV caches.

        Returns ``(last_logits, state)``: ``last_logits`` (B, vocab) are
        the logits predicting the token AFTER the prefix (bit-matching
        column ``Lp-1`` of the full forward), ``state`` is the decode
        pytree — per-layer ``(max_len, B, H, D)`` self-attention cache
        pairs (prefix written at rows ``[0, Lp)``), static cross-attention
        memory projections, and the source mask."""
        from ... import ndarray as F

        memory = self.encode(src_ids, src_valid_length)
        x = self._embed(F, self.tgt_embed, tgt_prefix)
        B = x.shape[0]
        vl_raw = None if src_valid_length is None else (
            src_valid_length.data if isinstance(src_valid_length, NDArray)
            else jnp.asarray(src_valid_length))
        self_kv, cross_kv = [], []
        for i in range(self.decoder._n):
            layer = getattr(self.decoder, f"layer{i}")
            x, (k_s, v_s), (k_m, v_m) = layer.prefill(
                x, memory, mem_valid_length=src_valid_length)
            kc, vc = layer.self_attn.init_cache(
                B, max_len, cache_dtype or k_s.dtype)
            zero = (0, 0, 0, 0)
            kc = jax.lax.dynamic_update_slice(kc, jnp.swapaxes(k_s, 0, 1),
                                              zero)
            vc = jax.lax.dynamic_update_slice(vc, jnp.swapaxes(v_s, 0, 1),
                                              zero)
            self_kv.append((kc, vc))
            cross_kv.append((k_m, v_m))
        out = self.decoder.ln(x)
        logits = self._logits(F, out[:, -1:, :])[:, 0]
        state = {"self_kv": tuple(self_kv), "cross_kv": tuple(cross_kv),
                 "mem_vl": vl_raw}
        return logits, state

    def decode_step(self, tokens, pos, state):
        """One O(1) incremental decode step: place ``tokens`` (B,) int32
        at absolute target position ``pos`` (a traced scalar; the number
        of tokens already cached) and return ``(logits, new_state)`` —
        ``logits`` (B, vocab) predict position ``pos + 1``'s token and
        bit-match column ``pos`` of a full re-forward."""
        from ... import ndarray as F

        x = self._embed_step(tokens, pos)
        mem_vl = state["mem_vl"]
        mem_vl_nd = None if mem_vl is None else NDArray(mem_vl)
        new_self = []
        for i in range(self.decoder._n):
            layer = getattr(self.decoder, f"layer{i}")
            x, kv = layer.step(x, state["self_kv"][i], pos,
                               state["cross_kv"][i],
                               mem_valid_length=mem_vl_nd)
            new_self.append(kv)
        out = self.decoder.ln(x)
        logits = self._logits(F, out)[:, 0]
        return logits, {"self_kv": tuple(new_self),
                        "cross_kv": state["cross_kv"], "mem_vl": mem_vl}

    def _embed_step(self, tokens, pos):
        """Single-position target embedding (token + absolute position)."""
        tok = tokens.data if isinstance(tokens, NDArray) else \
            jnp.asarray(tokens)
        B = tok.shape[0]
        ids = NDArray(tok.reshape(B, 1).astype(jnp.int32))
        pos_ids = NDArray(jnp.broadcast_to(
            jnp.asarray(pos, jnp.int32).reshape(1, 1), (B, 1)))
        return self.drop(self.tgt_embed(ids) * (self._units ** 0.5)
                         + self.pos_embed(pos_ids))

    def generate(self, src_ids, src_valid_length=None, max_new_tokens=32,
                 **kwargs):
        """KV-cached generation through a lazily-built (and cached)
        ``parallel.infer.InferStep``. Engine kwargs (``amp``, ``max_len``,
        ``bos_id``/``eos_id``/``pad_id``) configure the cached engine;
        the rest (``method``, ``top_k``, ``temperature``, ``seed``) pass
        through to ``InferStep.generate``. Returns ``(tokens, lengths)``
        NDArrays."""
        from ...parallel.infer import InferStep

        eng_keys = ("amp", "max_len", "bos_id", "eos_id", "pad_id")
        eng_kw = {k: kwargs.pop(k) for k in eng_keys if k in kwargs}
        cache_key = tuple(sorted(eng_kw.items()))
        steps = getattr(self, "_infer_steps", None)
        if steps is None:
            steps = {}
            object.__setattr__(self, "_infer_steps", steps)
        if cache_key not in steps:
            steps[cache_key] = InferStep(self, **eng_kw)
        return steps[cache_key].generate(
            src_ids, src_valid_length, max_new_tokens=max_new_tokens,
            **kwargs)


def transformer_base(**kwargs):
    return TransformerModel(units=512, hidden_size=2048, num_layers=6,
                            num_heads=8, **kwargs)


def transformer_big(**kwargs):
    return TransformerModel(units=1024, hidden_size=4096, num_layers=6,
                            num_heads=16, **kwargs)
