"""Transformer encoder-decoder for seq2seq (reference workload: GluonNLP
Transformer WMT En-De over contrib interleaved encdec attention ops
[unverified]; BASELINE.md config 4).

Pre-LN arrangement (more stable; graph fusion identical), flash attention
everywhere: causal self-attention in the decoder, cross-attention over
encoder memory."""

from __future__ import annotations

from ..block import HybridBlock
from ..nn import (
    Dense, Dropout, Embedding, HybridSequential, LayerNorm,
    MultiHeadAttention,
)

__all__ = ["TransformerEncoder", "TransformerDecoder", "TransformerModel",
           "transformer_base", "transformer_big"]


class _FFN(HybridBlock):
    def __init__(self, units, hidden_size, dropout, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ffn_1 = Dense(hidden_size, activation="relu", flatten=False)
            self.ffn_2 = Dense(units, flatten=False)
            self.drop = Dropout(dropout)

    def hybrid_forward(self, F, x):
        return self.drop(self.ffn_2(self.ffn_1(x)))


class TransformerEncoderLayer(HybridBlock):
    _remat_unit = True  # hybridize(remat=...): one checkpoint region/layer

    def __init__(self, units, hidden_size, num_heads, dropout, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ln1 = LayerNorm(in_channels=units)
            self.attn = MultiHeadAttention(units, num_heads, dropout=dropout)
            self.ln2 = LayerNorm(in_channels=units)
            self.ffn = _FFN(units, hidden_size, dropout)
            self.drop = Dropout(dropout)

    def hybrid_forward(self, F, x):
        # tags feed the names-based remat policy (remat='names:attn_out,
        # ffn_out' keeps exactly these resident); identity otherwise
        x = x + self.drop(F.checkpoint_name(self.attn(self.ln1(x)),
                                            name="attn_out"))
        return x + F.checkpoint_name(self.ffn(self.ln2(x)), name="ffn_out")


class TransformerDecoderLayer(HybridBlock):
    _remat_unit = True

    def __init__(self, units, hidden_size, num_heads, dropout, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ln1 = LayerNorm(in_channels=units)
            self.self_attn = MultiHeadAttention(
                units, num_heads, dropout=dropout, causal=True
            )
            self.ln2 = LayerNorm(in_channels=units)
            self.cross_attn = MultiHeadAttention(
                units, num_heads, dropout=dropout, self_attention=False
            )
            self.ln3 = LayerNorm(in_channels=units)
            self.ffn = _FFN(units, hidden_size, dropout)
            self.drop = Dropout(dropout)

    def hybrid_forward(self, F, x, memory):
        x = x + self.drop(F.checkpoint_name(self.self_attn(self.ln1(x)),
                                            name="attn_out"))
        x = x + self.drop(F.checkpoint_name(
            self.cross_attn(self.ln2(x), memory, memory), name="attn_out"))
        return x + F.checkpoint_name(self.ffn(self.ln3(x)), name="ffn_out")


class TransformerEncoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads, dropout,
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.layers = HybridSequential()
            for _ in range(num_layers):
                self.layers.add(
                    TransformerEncoderLayer(units, hidden_size, num_heads,
                                            dropout)
                )
            self.ln = LayerNorm(in_channels=units)

    def hybrid_forward(self, F, x):
        return self.ln(self.layers(x))


class TransformerDecoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads, dropout,
                 **kwargs):
        super().__init__(**kwargs)
        self._n = num_layers
        with self.name_scope():
            for i in range(num_layers):
                setattr(self, f"layer{i}",
                        TransformerDecoderLayer(units, hidden_size, num_heads,
                                                dropout))
            self.ln = LayerNorm(in_channels=units)

    def hybrid_forward(self, F, x, memory):
        for i in range(self._n):
            x = getattr(self, f"layer{i}")(x, memory)
        return self.ln(x)


class TransformerModel(HybridBlock):
    """forward(src_ids, tgt_ids) -> logits (B, T_tgt, vocab)."""

    def __init__(self, src_vocab=32768, tgt_vocab=32768, units=512,
                 hidden_size=2048, num_layers=6, num_heads=8, max_length=1024,
                 dropout=0.1, tie_weights=True, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        with self.name_scope():
            self.src_embed = Embedding(src_vocab, units, prefix="src_embed_")
            self.tgt_embed = Embedding(tgt_vocab, units, prefix="tgt_embed_")
            self.pos_embed = Embedding(max_length, units, prefix="pos_embed_")
            self.drop = Dropout(dropout)
            self.encoder = TransformerEncoder(
                num_layers, units, hidden_size, num_heads, dropout,
                prefix="enc_",
            )
            self.decoder = TransformerDecoder(
                num_layers, units, hidden_size, num_heads, dropout,
                prefix="dec_",
            )
            self._tied = tie_weights
            if not tie_weights:
                self.proj = Dense(tgt_vocab, flatten=False, prefix="proj_")

    def _embed(self, F, embed, ids):
        B, S = ids.shape[0], ids.shape[1]
        pos = F.arange(0, S).reshape(1, S).broadcast_to((B, S))
        return self.drop(embed(ids) * (self._units ** 0.5)
                         + self.pos_embed(pos))

    def hybrid_forward(self, F, src_ids, tgt_ids):
        memory = self.encoder(self._embed(F, self.src_embed, src_ids))
        out = self.decoder(self._embed(F, self.tgt_embed, tgt_ids), memory)
        if self._tied:
            w = self.tgt_embed.weight.data()
            return F.dot(out, w.T)
        return self.proj(out)


def transformer_base(**kwargs):
    return TransformerModel(units=512, hidden_size=2048, num_layers=6,
                            num_heads=8, **kwargs)


def transformer_big(**kwargs):
    return TransformerModel(units=1024, hidden_size=4096, num_layers=6,
                            num_heads=16, **kwargs)
