"""Vision model zoo (reference: ``gluon/model_zoo/vision/`` [unverified]):
resnet v1/v2 (18-152), vgg (11-19, +bn), mobilenet v1/v2/v3, densenet,
squeezenet, inception v3, alexnet. ``get_model(name)`` is the factory.

Pretrained-weight download is unavailable (zero-egress build); load local
``.params`` files via ``net.load_parameters`` instead.
"""

from ....base import MXNetError

_models = {}


def register_model(fn):
    _models[fn.__name__] = fn
    return fn


def get_model(name, **kwargs):
    name = name.lower()
    if name not in _models:
        raise MXNetError(
            f"model {name!r} is not in the zoo; available: {sorted(_models)}"
    )
    return _models[name](**kwargs)


# populate the registry (imports must come after register_model is defined);
# grab module __all__ lists BEFORE star imports shadow same-named factories
from . import resnet as _resnet  # noqa: E402
from . import alexnet as _alexnet  # noqa: E402
from . import vgg as _vgg  # noqa: E402
from . import mobilenet as _mobilenet  # noqa: E402
from . import squeezenet as _squeezenet  # noqa: E402
from . import densenet as _densenet  # noqa: E402
from . import inception as _inception  # noqa: E402

__all__ = ["get_model", "register_model"]
for _m in (_resnet, _alexnet, _vgg, _mobilenet, _squeezenet, _densenet,
           _inception):
    __all__ += _m.__all__

from .resnet import *  # noqa: F401,F403,E402
from .alexnet import *  # noqa: F401,F403,E402
from .vgg import *  # noqa: F401,F403,E402
from .mobilenet import *  # noqa: F401,F403,E402
from .squeezenet import *  # noqa: F401,F403,E402
from .densenet import *  # noqa: F401,F403,E402
from .inception import *  # noqa: F401,F403,E402
