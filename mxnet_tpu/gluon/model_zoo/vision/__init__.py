"""Vision model zoo (reference: ``gluon/model_zoo/vision/`` [unverified]).

Populated incrementally; ``get_model(name)`` is the factory entry point."""

from ....base import MXNetError

_models = {}


def register_model(fn):
    _models[fn.__name__] = fn
    return fn


def get_model(name, **kwargs):
    name = name.lower()
    if name not in _models:
        raise MXNetError(
            f"model {name!r} is not in the zoo; available: {sorted(_models)}"
        )
    return _models[name](**kwargs)


__all__ = ["get_model", "register_model"]
