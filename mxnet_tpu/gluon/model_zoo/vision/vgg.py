"""VGG 11/13/16/19 (+BN variants) (reference:
``gluon/model_zoo/vision/vgg.py`` [unverified])."""

from __future__ import annotations

from ...nn import (
    Activation, BatchNorm, Conv2D, Dense, Dropout, HybridSequential, MaxPool2D,
)
from ...block import HybridBlock
from . import register_model

__all__ = [
    "VGG", "vgg11", "vgg13", "vgg16", "vgg19",
    "vgg11_bn", "vgg13_bn", "vgg16_bn", "vgg19_bn", "get_vgg",
]

vgg_spec = {
    11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
    13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
    16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
    19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512]),
}


class VGG(HybridBlock):
    def __init__(self, layers, filters, classes=1000, batch_norm=False,
                 **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(filters)
        with self.name_scope():
            self.features = self._make_features(layers, filters, batch_norm)
            self.features.add(Dense(4096, activation="relu", flatten=True))
            self.features.add(Dropout(rate=0.5))
            self.features.add(Dense(4096, activation="relu"))
            self.features.add(Dropout(rate=0.5))
            self.output = Dense(classes)

    def _make_features(self, layers, filters, batch_norm):
        featurizer = HybridSequential(prefix="")
        for i, num in enumerate(layers):
            for _ in range(num):
                featurizer.add(
                    Conv2D(filters[i], kernel_size=3, padding=1)
                )
                if batch_norm:
                    featurizer.add(BatchNorm())
                featurizer.add(Activation("relu"))
            featurizer.add(MaxPool2D(strides=2))
        return featurizer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


def get_vgg(num_layers, pretrained=False, **kwargs):
    layers, filters = vgg_spec[num_layers]
    net = VGG(layers, filters, **kwargs)
    return net


def _make(layers, bn):
    def f(**kwargs):
        if bn:
            kwargs["batch_norm"] = True
        return get_vgg(layers, **kwargs)

    f.__name__ = f"vgg{layers}" + ("_bn" if bn else "")
    return register_model(f)


vgg11 = _make(11, False)
vgg13 = _make(13, False)
vgg16 = _make(16, False)
vgg19 = _make(19, False)
vgg11_bn = _make(11, True)
vgg13_bn = _make(13, True)
vgg16_bn = _make(16, True)
vgg19_bn = _make(19, True)
