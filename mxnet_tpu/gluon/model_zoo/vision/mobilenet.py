"""MobileNet v1/v2/v3 (reference: ``gluon/model_zoo/vision/mobilenet.py`` +
GluonCV mobilenetv3 [unverified]). Depthwise convs = grouped Conv2D, which
XLA lowers to MXU-friendly batched matmuls."""

from __future__ import annotations

from ...nn import (
    Activation, BatchNorm, Conv2D, Dense, Flatten, GlobalAvgPool2D,
    HybridSequential,
)
from ...block import HybridBlock
from . import register_model

__all__ = [
    "MobileNet", "MobileNetV2", "MobileNetV3",
    "mobilenet1_0", "mobilenet0_75", "mobilenet0_5", "mobilenet0_25",
    "mobilenet_v2_1_0", "mobilenet_v2_0_75", "mobilenet_v2_0_5",
    "mobilenet_v2_0_25",
    "mobilenet_v3_large", "mobilenet_v3_small",
]


class RELU6(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.clip(x, 0, 6)


class HSwish(HybridBlock):
    def hybrid_forward(self, F, x):
        return x * F.clip(x + 3, 0, 6) / 6


class HSigmoid(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.clip(x + 3, 0, 6) / 6


def _add_conv(out, channels=1, kernel=1, stride=1, pad=0, num_group=1,
              active=True, relu6=False):
    out.add(Conv2D(channels, kernel, stride, pad, groups=num_group,
                   use_bias=False))
    out.add(BatchNorm(scale=True))
    if active:
        out.add(RELU6() if relu6 else Activation("relu"))


def _add_conv_dw(out, dw_channels, channels, stride, relu6=False):
    _add_conv(out, dw_channels, kernel=3, stride=stride, pad=1,
              num_group=dw_channels, relu6=relu6)
    _add_conv(out, channels, relu6=relu6)


class LinearBottleneck(HybridBlock):
    """MobileNetV2 inverted residual."""

    def __init__(self, in_channels, channels, t, stride, **kwargs):
        super().__init__(**kwargs)
        self.use_shortcut = stride == 1 and in_channels == channels
        with self.name_scope():
            self.out = HybridSequential()
            if t != 1:
                _add_conv(self.out, in_channels * t, relu6=True)
            _add_conv(self.out, in_channels * t, kernel=3, stride=stride,
                      pad=1, num_group=in_channels * t, relu6=True)
            _add_conv(self.out, channels, active=False, relu6=True)

    def hybrid_forward(self, F, x):
        out = self.out(x)
        if self.use_shortcut:
            out = out + x
        return out


class MobileNet(HybridBlock):
    """MobileNetV1."""

    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = HybridSequential(prefix="")
            with self.features.name_scope():
                _add_conv(self.features, channels=int(32 * multiplier),
                          kernel=3, pad=1, stride=2)
                dw_channels = [
                    int(x * multiplier)
                    for x in [32, 64] + [128] * 2 + [256] * 2 + [512] * 6 + [1024]
                ]
                channels = [
                    int(x * multiplier)
                    for x in [64] + [128] * 2 + [256] * 2 + [512] * 6 + [1024] * 2
                ]
                strides = [1, 2, 1, 2, 1, 2, 1, 1, 1, 1, 1, 2, 1]
                for dwc, c, s in zip(dw_channels, channels, strides):
                    _add_conv_dw(self.features, dw_channels=dwc, channels=c,
                                 stride=s)
                self.features.add(GlobalAvgPool2D())
                self.features.add(Flatten())
            self.output = Dense(classes)

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


class MobileNetV2(HybridBlock):
    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = HybridSequential(prefix="features_")
            with self.features.name_scope():
                _add_conv(self.features, int(32 * multiplier), kernel=3,
                          stride=2, pad=1, relu6=True)
                in_channels_group = [
                    int(x * multiplier)
                    for x in [32] + [16] + [24] * 2 + [32] * 3 + [64] * 4
                    + [96] * 3 + [160] * 3
                ]
                channels_group = [
                    int(x * multiplier)
                    for x in [16] + [24] * 2 + [32] * 3 + [64] * 4 + [96] * 3
                    + [160] * 3 + [320]
                ]
                ts = [1] + [6] * 16
                strides = [1, 2, 1, 2, 1, 1, 2, 1, 1, 1, 1, 1, 1, 2, 1, 1, 1]
                for in_c, c, t, s in zip(
                    in_channels_group, channels_group, ts, strides
                ):
                    self.features.add(
                        LinearBottleneck(in_channels=in_c, channels=c, t=t,
                                         stride=s)
                    )
                last_channels = (
                    int(1280 * multiplier) if multiplier > 1.0 else 1280
                )
                _add_conv(self.features, last_channels, relu6=True)
                self.features.add(GlobalAvgPool2D())
            self.output = HybridSequential(prefix="output_")
            with self.output.name_scope():
                self.output.add(
                    Conv2D(classes, 1, use_bias=False, prefix="pred_"),
                    Flatten(),
                )

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


class _SE(HybridBlock):
    def __init__(self, channels, reduction=4, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.pool = GlobalAvgPool2D()
            self.fc1 = Conv2D(channels // reduction, 1, use_bias=True)
            self.fc2 = Conv2D(channels, 1, use_bias=True)
            self.hsig = HSigmoid()

    def hybrid_forward(self, F, x):
        w = self.pool(x)
        w = F.relu(self.fc1(w))
        w = self.hsig(self.fc2(w))
        return x * w


class _MBV3Block(HybridBlock):
    def __init__(self, in_c, exp_c, out_c, kernel, stride, se, act,
                 **kwargs):
        super().__init__(**kwargs)
        self.use_shortcut = stride == 1 and in_c == out_c
        act_layer = HSwish() if act == "hswish" else Activation("relu")
        with self.name_scope():
            self.out = HybridSequential()
            if exp_c != in_c:
                self.out.add(Conv2D(exp_c, 1, use_bias=False), BatchNorm())
                self.out.add(HSwish() if act == "hswish" else Activation("relu"))
            self.out.add(
                Conv2D(exp_c, kernel, stride, kernel // 2, groups=exp_c,
                       use_bias=False),
                BatchNorm(),
            )
            if se:
                self.out.add(_SE(exp_c))
            self.out.add(act_layer)
            self.out.add(Conv2D(out_c, 1, use_bias=False), BatchNorm())

    def hybrid_forward(self, F, x):
        out = self.out(x)
        if self.use_shortcut:
            out = out + x
        return out


_V3_LARGE = [
    # in, exp, out, k, s, se, act
    (16, 16, 16, 3, 1, False, "relu"),
    (16, 64, 24, 3, 2, False, "relu"),
    (24, 72, 24, 3, 1, False, "relu"),
    (24, 72, 40, 5, 2, True, "relu"),
    (40, 120, 40, 5, 1, True, "relu"),
    (40, 120, 40, 5, 1, True, "relu"),
    (40, 240, 80, 3, 2, False, "hswish"),
    (80, 200, 80, 3, 1, False, "hswish"),
    (80, 184, 80, 3, 1, False, "hswish"),
    (80, 184, 80, 3, 1, False, "hswish"),
    (80, 480, 112, 3, 1, True, "hswish"),
    (112, 672, 112, 3, 1, True, "hswish"),
    (112, 672, 160, 5, 2, True, "hswish"),
    (160, 960, 160, 5, 1, True, "hswish"),
    (160, 960, 160, 5, 1, True, "hswish"),
]
_V3_SMALL = [
    (16, 16, 16, 3, 2, True, "relu"),
    (16, 72, 24, 3, 2, False, "relu"),
    (24, 88, 24, 3, 1, False, "relu"),
    (24, 96, 40, 5, 2, True, "hswish"),
    (40, 240, 40, 5, 1, True, "hswish"),
    (40, 240, 40, 5, 1, True, "hswish"),
    (40, 120, 48, 5, 1, True, "hswish"),
    (48, 144, 48, 5, 1, True, "hswish"),
    (48, 288, 96, 5, 2, True, "hswish"),
    (96, 576, 96, 5, 1, True, "hswish"),
    (96, 576, 96, 5, 1, True, "hswish"),
]


class MobileNetV3(HybridBlock):
    def __init__(self, spec, last_exp, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = HybridSequential(prefix="")
            self.features.add(
                Conv2D(16, 3, 2, 1, use_bias=False), BatchNorm(), HSwish()
            )
            for in_c, exp_c, out_c, k, s, se, act in spec:
                self.features.add(
                    _MBV3Block(in_c, exp_c, out_c, k, s, se, act)
                )
            self.features.add(
                Conv2D(last_exp, 1, use_bias=False), BatchNorm(), HSwish()
            )
            self.features.add(GlobalAvgPool2D())
            self.features.add(Conv2D(1280, 1, use_bias=True), HSwish())
            self.output = HybridSequential()
            self.output.add(Conv2D(classes, 1, use_bias=True), Flatten())

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


def _make_v1(mult, name):
    def f(**kwargs):
        return MobileNet(mult, **kwargs)

    f.__name__ = name
    return register_model(f)


def _make_v2(mult, name):
    def f(**kwargs):
        return MobileNetV2(mult, **kwargs)

    f.__name__ = name
    return register_model(f)


mobilenet1_0 = _make_v1(1.0, "mobilenet1_0")
mobilenet0_75 = _make_v1(0.75, "mobilenet0_75")
mobilenet0_5 = _make_v1(0.5, "mobilenet0_5")
mobilenet0_25 = _make_v1(0.25, "mobilenet0_25")
mobilenet_v2_1_0 = _make_v2(1.0, "mobilenet_v2_1_0")
mobilenet_v2_0_75 = _make_v2(0.75, "mobilenet_v2_0_75")
mobilenet_v2_0_5 = _make_v2(0.5, "mobilenet_v2_0_5")
mobilenet_v2_0_25 = _make_v2(0.25, "mobilenet_v2_0_25")


@register_model
def mobilenet_v3_large(**kwargs):
    return MobileNetV3(_V3_LARGE, 960, **kwargs)


@register_model
def mobilenet_v3_small(**kwargs):
    return MobileNetV3(_V3_SMALL, 576, **kwargs)
