"""BERT (reference workload: GluonNLP BERT-base over the contrib interleaved
attention ops + ``src/operator/nn/layer_norm.cc`` [unverified]; BASELINE.md
config 3 = BERT-base pretrain).

TPU-first: attention is the Pallas flash kernel (O(S) memory), the whole
encoder stages into one XLA program under ``hybridize()``, and embeddings +
MLM head share weights like the original."""

from __future__ import annotations

import math

from ...base import MXNetError
from ..block import HybridBlock
from ..nn import (
    Dense, Dropout, Embedding, GELU, HybridSequential, LayerNorm,
    MultiHeadAttention,
)

__all__ = [
    "BERTEncoderLayer", "BERTEncoder", "BERTModel",
    "BERTForPretraining", "BERTEncoderForGeneration",
    "bert_base", "bert_large", "get_bert",
]


class PositionwiseFFN(HybridBlock):
    def __init__(self, units, hidden_size, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ffn_1 = Dense(hidden_size, flatten=False, prefix="ffn1_")
            self.act = GELU()
            self.ffn_2 = Dense(units, flatten=False, prefix="ffn2_")
            self.drop = Dropout(dropout)

    def hybrid_forward(self, F, x):
        return self.drop(self.ffn_2(self.act(self.ffn_1(x))))


class BERTEncoderLayer(HybridBlock):
    """Post-LN transformer encoder layer (original BERT arrangement)."""

    _remat_unit = True  # hybridize(remat=...): one checkpoint region/layer

    def __init__(self, units, hidden_size, num_heads, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.attention = MultiHeadAttention(
                units, num_heads, dropout=dropout, self_attention=True,
                prefix="attn_",
            )
            self.ln_attn = LayerNorm(in_channels=units, prefix="ln_attn_")
            self.ffn = PositionwiseFFN(units, hidden_size, dropout,
                                       prefix="ffn_")
            self.ln_ffn = LayerNorm(in_channels=units, prefix="ln_ffn_")
            self.drop = Dropout(dropout)

    def hybrid_forward(self, F, x, valid_length=None):
        # tags feed the names-based remat policy (remat='names:attn_out,
        # ffn_out'); identity otherwise
        attn = self.drop(F.checkpoint_name(
            self.attention(x, valid_length=valid_length), name="attn_out"))
        x = self.ln_attn(x + attn)
        ffn = F.checkpoint_name(self.ffn(x), name="ffn_out")
        return self.ln_ffn(x + ffn)


class BERTEncoder(HybridBlock):
    """Transformer encoder stack.

    ``remat=True`` wraps each layer in ``jax.checkpoint`` when traced:
    the backward recomputes the layer forward instead of loading saved
    pre-activations — the TPU-native activation-memory/bandwidth trade
    (profiled: the FFN fusions are write-bound saving both the pre-GELU
    and post-GELU (B,S,4H) tensors; remat trades those HBM writes for
    MXU recompute, which this chip has headroom for)."""

    def __init__(self, num_layers, units, hidden_size, num_heads,
                 dropout=0.1, remat=False, **kwargs):
        super().__init__(**kwargs)
        self._remat = remat
        with self.name_scope():
            self.layers = HybridSequential(prefix="layers_")
            for _ in range(num_layers):
                self.layers.add(
                    BERTEncoderLayer(units, hidden_size, num_heads, dropout)
                )

    def hybrid_forward(self, F, x, valid_length=None):
        import jax as _jax

        if self._remat and isinstance(x.data, _jax.core.Tracer):
            from ...ndarray.ndarray import NDArray as _ND
            from ... import random as _random
            from ... import remat as _remat_mod

            # remat accepts True (recompute everything) or any policy
            # from mxnet_tpu.remat ('dots_saveable', 'names:...', ...)
            policy = _remat_mod.resolve_policy(self._remat)

            # each layer gets its PRNG key as an explicit operand: the key
            # supply must not be split inside the checkpointed trace (tracer
            # leak), and the recompute must replay identical dropout masks.
            # Outside a supply scope (e.g. the deferred-init shape probe) use
            # a constant key — drawing from the global stateful stream inside
            # a trace would shift unrelated draws (parameter init!)
            supply = _random.current_key_supply()
            for layer in self.layers:
                key = supply.next() if supply is not None \
                    else _jax.random.PRNGKey(0)
                if valid_length is None:
                    def f(a, k, _l=layer):
                        with _random.key_supply(k):
                            return _l(_ND(a)).data

                    x = _ND(_jax.checkpoint(f, policy=policy)(x.data, key))
                else:
                    def f(a, k, vl, _l=layer):
                        with _random.key_supply(k):
                            return _l(_ND(a), _ND(vl)).data

                    x = _ND(_jax.checkpoint(f, policy=policy)(
                        x.data, key, valid_length.data))
            return x
        for layer in self.layers:
            x = layer(x, valid_length)
        return x


class BERTModel(HybridBlock):
    """Embeddings + encoder + pooler.

    forward(token_ids, token_types) -> (sequence_output, pooled_output)
    token_ids/token_types: (B, S) int32.
    """

    def __init__(self, vocab_size=30522, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=512,
                 type_vocab_size=2, dropout=0.1, remat=False, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._vocab_size = vocab_size
        with self.name_scope():
            self.word_embed = Embedding(vocab_size, units, prefix="word_")
            self.token_type_embed = Embedding(type_vocab_size, units,
                                              prefix="type_")
            self.position_embed = Embedding(max_length, units, prefix="pos_")
            self.embed_ln = LayerNorm(in_channels=units, prefix="embed_ln_")
            self.embed_drop = Dropout(dropout)
            self.encoder = BERTEncoder(
                num_layers, units, hidden_size, num_heads, dropout,
                remat=remat, prefix="enc_",
            )
            self.pooler = Dense(units, activation="tanh", flatten=False,
                                prefix="pooler_")

    def hybrid_forward(self, F, token_ids, token_types=None,
                       valid_length=None):
        B, S = token_ids.shape[0], token_ids.shape[1]
        positions = F.arange(0, S).reshape(1, S).broadcast_to((B, S))
        emb = self.word_embed(token_ids) + self.position_embed(positions)
        if token_types is not None:
            emb = emb + self.token_type_embed(token_types)
        emb = self.embed_drop(self.embed_ln(emb))
        seq = self.encoder(emb, valid_length)
        pooled = self.pooler(seq[:, 0, :])
        return seq, pooled


class BERTForPretraining(HybridBlock):
    """MLM + NSP heads (decoder weight tied to word embedding)."""

    def __init__(self, bert: BERTModel = None, **bert_kwargs):
        super().__init__(prefix=bert_kwargs.pop("prefix", None),
                         params=bert_kwargs.pop("params", None))
        with self.name_scope():
            self.bert = bert if bert is not None else BERTModel(**bert_kwargs)
            units = self.bert._units
            self.mlm_transform = Dense(units, flatten=False, prefix="mlmt_")
            self.mlm_act = GELU()
            self.mlm_ln = LayerNorm(in_channels=units, prefix="mlm_ln_")
            self.nsp = Dense(2, flatten=False, prefix="nsp_")

    def hybrid_forward(self, F, token_ids, token_types=None,
                       valid_length=None):
        seq, pooled = self.bert(token_ids, token_types, valid_length)
        h = self.mlm_ln(self.mlm_act(self.mlm_transform(seq)))
        # tied decoder: logits = h @ word_embedding^T
        embed_w = self.bert.word_embed.weight.data()
        mlm_logits = F.dot(h, embed_w.T)
        nsp_logits = self.nsp(pooled)
        return mlm_logits, nsp_logits


class BERTEncoderForGeneration(HybridBlock):
    """BERT as the memory encoder of a seq2seq generator.

    Adapts ``BERTModel``'s ``(token_ids, token_types, valid_length)``
    call signature to the ``TransformerModel(encoder=...)`` contract
    ``(src_ids, valid_length) -> (B, S, units)`` — the "BERT-as-encoder"
    prefill configuration: bucket-padded prompts run through the (deep,
    bidirectional) BERT stack once at prefill, and the decoder's
    KV-cached incremental steps attend to the resulting memory."""

    def __init__(self, bert: BERTModel, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.bert = bert

    def hybrid_forward(self, F, src_ids, valid_length=None):
        seq, _ = self.bert(src_ids, None, valid_length)
        return seq


_BERT_SPECS = {
    "bert_base": dict(units=768, hidden_size=3072, num_layers=12,
                      num_heads=12),
    "bert_large": dict(units=1024, hidden_size=4096, num_layers=24,
                       num_heads=16),
}


def get_bert(name="bert_base", **kwargs):
    if name not in _BERT_SPECS:
        raise MXNetError(f"unknown bert spec {name}")
    spec = dict(_BERT_SPECS[name])
    spec.update(kwargs)
    return BERTModel(**spec)


def bert_base(**kwargs):
    return get_bert("bert_base", **kwargs)


def bert_large(**kwargs):
    return get_bert("bert_large", **kwargs)
