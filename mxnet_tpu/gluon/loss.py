"""Loss blocks (reference: ``python/mxnet/gluon/loss.py`` [unverified])."""

from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from .block import HybridBlock

__all__ = [
    "Loss",
    "L2Loss",
    "L1Loss",
    "SigmoidBinaryCrossEntropyLoss",
    "SigmoidBCELoss",
    "SoftmaxCrossEntropyLoss",
    "SoftmaxCELoss",
    "KLDivLoss",
    "CTCLoss",
    "HuberLoss",
    "HingeLoss",
    "SquaredHingeLoss",
    "LogisticLoss",
    "TripletLoss",
    "PoissonNLLLoss",
    "CosineEmbeddingLoss",
]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = loss * sample_weight
    if weight is not None:
        assert isinstance(weight, (int, float)), "weight must be a number"
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return x.reshape(y.shape)


class Loss(HybridBlock):
    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return f"{self.__class__.__name__}(batch_axis={self._batch_axis}, w={self._weight})"

    def hybrid_forward(self, F, x, *args, **kwargs):  # pragma: no cover
        raise NotImplementedError


def _batch_mean(F, loss, batch_axis):
    axes = tuple(i for i in range(loss.ndim) if i != batch_axis)
    return loss.mean(axis=axes) if axes else loss


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(label - pred)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None, pos_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            if pos_weight is None:
                loss = F.relu(pred) - pred * label + F.Activation(
                    -F.abs(pred), act_type="softrelu"
                )
            else:
                log_weight = 1 + (pos_weight - 1) * label
                loss = (
                    pred
                    - pred * label
                    + log_weight
                    * (
                        F.Activation(-F.abs(pred), act_type="softrelu")
                        + F.relu(-pred)
                    )
                )
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(
                    F.log(pred + eps) * label
                    + F.log(1.0 - pred + eps) * (1.0 - label)
                )
            else:
                loss = -(
                    F.log(pred + eps) * label * pos_weight
                    + F.log(1.0 - pred + eps) * (1.0 - label)
                )
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """Softmax + CE fused (reference: gluon ``SoftmaxCrossEntropyLoss``;
    kernel ``src/operator/nn/softmax.cc``)."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(F, label, pred)
            loss = -(pred * label).sum(axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class CTCLoss(Loss):
    """Connectionist temporal classification (reference:
    ``src/operator/nn/ctc_loss.cc`` [unverified]).

    Layouts: 'NTC' (default) or 'TNC'. Implemented as the standard
    log-alpha recursion with a ``lax.scan`` over time — compiles to one
    fused XLA loop (no dynamic shapes)."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        assert layout in ("NTC", "TNC")
        assert label_layout in ("NT", "TN")
        self._layout = layout
        self._label_layout = label_layout
        batch_axis = label_layout.find("N")
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        import jax
        import jax.numpy as jnp
        from ..ndarray.ndarray import NDArray
        from ..imperative import invoke_fn

        if self._layout == "TNC":
            pred = pred.swapaxes(0, 1)
        if self._label_layout == "TN":
            label = label.swapaxes(0, 1)

        T = pred.shape[1]
        L = label.shape[1]

        def ctc(logits, labels, pl, ll):
            # logits (N,T,C); labels (N,L) int; blank index = 0 (mxnet default)
            logp = jax.nn.log_softmax(logits, axis=-1)
            N = logits.shape[0]
            S = 2 * L + 1
            lab = labels.astype(jnp.int32)
            # extended label sequence: blank, l1, blank, l2, ... blank
            ext = jnp.zeros((N, S), jnp.int32)
            ext = ext.at[:, 1::2].set(lab)
            neg_inf = -1e30
            # allowed skip transitions: ext[s] != ext[s-2] and ext[s] != blank
            skip_ok = jnp.concatenate(
                [
                    jnp.zeros((N, 2), bool),
                    (ext[:, 2:] != ext[:, :-2]) & (ext[:, 2:] != 0),
                ],
                axis=1,
            )
            alpha0 = jnp.full((N, S), neg_inf)
            alpha0 = alpha0.at[:, 0].set(logp[:, 0, 0])
            alpha0 = alpha0.at[:, 1].set(
                jnp.take_along_axis(logp[:, 0, :], ext[:, 1:2], axis=1)[:, 0]
            )

            def step(alpha, logp_t):
                shift1 = jnp.concatenate(
                    [jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1
                )
                shift2 = jnp.concatenate(
                    [jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1
                )
                shift2 = jnp.where(skip_ok, shift2, neg_inf)
                merged = jnp.logaddexp(jnp.logaddexp(alpha, shift1), shift2)
                emit = jnp.take_along_axis(logp_t, ext, axis=1)
                new_alpha = merged + emit
                return new_alpha, new_alpha

            _, alphas = jax.lax.scan(step, alpha0, jnp.moveaxis(logp, 1, 0)[1:])
            alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # (T,N,S)
            t_idx = (pl - 1).astype(jnp.int32)
            last = alphas[t_idx, jnp.arange(N)]  # (N,S)
            s_last = 2 * ll.astype(jnp.int32)
            p_blank = jnp.take_along_axis(last, s_last[:, None], axis=1)[:, 0]
            p_label = jnp.take_along_axis(
                last, jnp.maximum(s_last - 1, 0)[:, None], axis=1
            )[:, 0]
            return -jnp.logaddexp(p_blank, p_label)

        if pred_lengths is None:
            pl = jnp.full((pred.shape[0],), T, jnp.int32)
        else:
            pl = pred_lengths.data.astype(jnp.int32)
        if label_lengths is None:
            ll = jnp.full((pred.shape[0],), L, jnp.int32)
        else:
            ll = label_lengths.data.astype(jnp.int32)
        loss = invoke_fn(lambda lg, lb: ctc(lg, lb, pl, ll), pred, label)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class HuberLoss(Loss):
    def __init__(self, rho=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = F.where(
            loss > self._rho,
            loss - 0.5 * self._rho,
            (0.5 / self._rho) * F.square(loss),
        )
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed", **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format
        if self._label_format not in ["signed", "binary"]:
            raise MXNetError(f"label_format must be signed or binary, got {label_format}")

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = F.relu(pred) - pred * label + F.Activation(
            -F.abs(pred), act_type="softrelu"
        )
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative, sample_weight=None):
        positive = _reshape_like(F, positive, pred)
        negative = _reshape_like(F, negative, pred)
        loss = (
            F.square(pred - positive).sum(axis=tuple(range(1, pred.ndim)))
            - F.square(pred - negative).sum(axis=tuple(range(1, pred.ndim)))
        )
        loss = F.relu(loss + self._margin)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def hybrid_forward(self, F, pred, target, sample_weight=None, epsilon=1e-08):
        target = _reshape_like(F, target, pred)
        if self._from_logits:
            loss = F.exp(pred) - target * pred
        else:
            loss = pred - target * F.log(pred + epsilon)
        if self._compute_full:
            stirling = (
                target * F.log(target + 1e-12) - target
                + 0.5 * F.log(2 * target * _np.pi + 1e-12)
            )
            stirling = stirling * (target > 1)
            loss = loss + stirling
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss.mean()


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        input1 = _reshape_like(F, input1, input2)
        cos = (input1 * input2).sum(axis=-1) / (
            input1.norm(axis=-1) * input2.norm(axis=-1) + 1e-12
        )
        label = label.reshape((-1,))
        loss = F.where(
            label == 1, 1.0 - cos, F.relu(cos - self._margin)
        )
        return _apply_weighting(F, loss, self._weight, sample_weight)
