"""Block / HybridBlock (reference: ``python/mxnet/gluon/block.py``
[unverified]) and the CachedOp analogue (reference:
``src/imperative/cached_op.cc``).

TPU-first design (SURVEY.md §7): ``hybridize()`` does NOT trace into a
symbolic IR — it stages the block's (pure) forward through ``jax.jit`` so the
whole forward becomes one XLA executable. The pieces:

- Parameters enter the staged function as *traced arguments* (via a
  ``param_override`` scope), so weight updates never retrigger compilation.
- Stochastic ops draw from a per-call traced PRNG key (``random.key_supply``),
  keeping dropout random across steps while the program stays pure.
- Mutable aux states (BatchNorm moving stats) are captured by an "aux sink"
  during tracing and returned as extra outputs; the wrapper rebinds the real
  arrays after each call — the functional replacement for the reference's
  in-place aux writes.
- Autograd over a staged call records ONE tape node whose vjp is the jitted
  program's vjp — the analogue of CachedOp's backward graph.
"""

from __future__ import annotations

import json
import os
import re
import threading
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from ..context import current_context
from ..ndarray.ndarray import NDArray
from .. import compile_cache as _cc
from .. import ndarray as nd_namespace
from .. import random as _random
from .. import telemetry as _tel
from .parameter import (
    DeferredInitializationError,
    Parameter,
    ParameterDict,
    param_override,
)

__all__ = ["Block", "HybridBlock", "SymbolBlock", "CachedOp"]


# --------------------------------------------------------- thread-local state
_TLS = threading.local()


def _current_aux_sink():
    stack = getattr(_TLS, "aux_stack", None)
    return stack[-1] if stack else None


class _aux_scope:
    def __init__(self, sink):
        self._sink = sink

    def __enter__(self):
        if not hasattr(_TLS, "aux_stack"):
            _TLS.aux_stack = []
        _TLS.aux_stack.append(self._sink)
        return self._sink

    def __exit__(self, *exc):
        _TLS.aux_stack.pop()
        return False


def _in_trace() -> bool:
    return getattr(_TLS, "trace_depth", 0) > 0


def _in_probe() -> bool:
    return getattr(_TLS, "probe", False)


class _probe_scope:
    """Shape-inference probe: layers resolve deferred *shapes* but must not
    materialize parameter arrays (the probe runs under jax.eval_shape, where
    any array created would be a tracer and leak)."""

    def __enter__(self):
        self._prev = getattr(_TLS, "probe", False)
        _TLS.probe = True
        return self

    def __exit__(self, *exc):
        _TLS.probe = self._prev
        return False


class _trace_scope:
    """Marks 'we are inside a CachedOp trace': nested hybridized children run
    their eager bodies (the whole subtree belongs to one XLA program)."""

    def __enter__(self):
        _TLS.trace_depth = getattr(_TLS, "trace_depth", 0) + 1
        return self

    def __exit__(self, *exc):
        _TLS.trace_depth -= 1
        return False


# ------------------------------------------------------------------ namescope
class _BlockScope:
    """Counter-based auto-naming (reference: ``_BlockScope`` +
    ``name.NameManager`` for top-level blocks)."""

    _current = threading.local()
    _global_counter = {}  # hint -> count, for blocks created outside a scope

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None
        self._name_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                count = _BlockScope._global_counter.get(hint, 0)
                _BlockScope._global_counter[hint] = count + 1
                prefix = f"{hint}{count}_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            prefix = f"{hint}{count}_"
            current._counter[hint] = count + 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        if self._block._empty_prefix:
            return
        _BlockScope._current.value = self._old_scope


# ----------------------------------------------------------------------- Block
class Block:
    """Base container for layers and models (imperative path)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") else self._prefix
        self._scope = _BlockScope(self)
        self._children = OrderedDict()
        self._reg_params = {}
        self._forward_hooks = OrderedDict()
        self._forward_pre_hooks = OrderedDict()
        self._hook_counter = 0

    def _alias(self):
        return self.__class__.__name__.lower()

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(
            f"  ({key}): {_indent(repr(block), 2)}"
            for key, block in self._children.items()
        )
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        """Register Parameters and child Blocks (reference behavior)."""
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and not isinstance(
                value, type(existing)
            ):
                raise TypeError(
                    f"changing attribute type for {name} from {type(existing)} "
                    f"to {type(value)} is not allowed"
                )
        if isinstance(value, Block):
            self.register_child(value, name)
        elif isinstance(value, Parameter):
            assert name not in self._reg_params or self._reg_params[name] is value, (
                "Overriding Parameter attribute %s is not allowed. "
                "If you want to share parameters between blocks, please set "
                "'params' at Block construction instead." % name
            )
            self._reg_params[name] = value
        super().__setattr__(name, value)

    def _check_container_with_block(self):
        pass

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None) -> ParameterDict:
        self._check_container_with_block()
        ret = ParameterDict(self._params.prefix)
        if not select:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update(
                {
                    name: value
                    for name, value in self.params.items()
                    if pattern.match(name)
                }
            )
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks, self._hook_counter)
        self._forward_pre_hooks[self._hook_counter] = hook
        self._hook_counter += 1
        return handle

    def register_forward_hook(self, hook):
        handle = _HookHandle(self._forward_hooks, self._hook_counter)
        self._forward_hooks[self._hook_counter] = hook
        self._hook_counter += 1
        return handle

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        from .. import initializer

        if init is None:
            init = initializer.Uniform()
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def zero_grad(self):
        self.collect_params().zero_grad()

    # -------------------------------------------------------------- save/load
    def save_parameters(self, filename, deduplicate=False):
        params = self._collect_params_with_prefix()
        if deduplicate:
            seen = {}
            out = {}
            for name, param in params.items():
                if id(param) in seen:
                    continue
                seen[id(param)] = name
                out[name] = param
            params = out
        arg_dict = {name: param._check_and_get() for name, param in params.items()}
        from ..ndarray import save as nd_save

        nd_save(filename, arg_dict)

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + name: param for name, param in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):
        from ..ndarray import load as nd_load

        loaded = nd_load(filename)
        params = self._collect_params_with_prefix()
        if not loaded and not params:
            return
        if not any("." in k for k in loaded.keys()):
            # legacy full-name format saved via ParameterDict.save
            del loaded
            self.collect_params().load(
                filename, ctx, allow_missing, ignore_extra, self.prefix,
                cast_dtype=cast_dtype, dtype_source=dtype_source,
            )
            return
        if not allow_missing:
            for name in params.keys():
                if name not in loaded:
                    raise MXNetError(
                        f"parameter {name} missing in {filename}; "
                        "set allow_missing=True to skip"
                    )
        for name in loaded:
            if name not in params:
                if not ignore_extra:
                    raise MXNetError(
                        f"parameter {name} from {filename} not found in model; "
                        "set ignore_extra=True to skip"
                    )
                continue
            params[name].set_data(loaded[name])

    save_params = save_parameters
    load_params = load_parameters

    # ------------------------------------------------------------------ call
    def __call__(self, *args, **kwargs):
        # hooks see kwargs inputs too (appended, keeping the reference's
        # (block, inputs[, output]) hook arity)
        hook_args = args + tuple(kwargs.values()) if kwargs else args
        for hook in self._forward_pre_hooks.values():
            hook(self, hook_args)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_hooks.values():
            hook(self, hook_args, out)
        return out

    def forward(self, *args, **kwargs):  # pragma: no cover - abstract
        raise NotImplementedError

    def summary(self, *inputs):
        """Print a per-layer summary (reference: ``Block.summary``)."""
        summary_rec = OrderedDict()
        hooks = []

        def _make_hook(name, block):
            def hook(_blk, _in, out):
                outs = out if isinstance(out, (list, tuple)) else [out]
                shapes = [tuple(o.shape) for o in outs if isinstance(o, NDArray)]
                n_params = sum(
                    int(_np.prod(p.shape))
                    for p in block._reg_params.values()
                    if p._shape_known()
                )
                summary_rec[name] = (block.__class__.__name__, shapes, n_params)

            return hook

        for name, child in self._flat_children():
            hooks.append(child.register_forward_hook(_make_hook(name, child)))
        try:
            self(*inputs)
        finally:
            for h in hooks:
                h.detach()
        lines = [
            f"{'Layer (type)':<40}{'Output Shape':<30}{'Param #':<12}",
            "=" * 82,
        ]
        total = 0
        for name, (cls, shapes, n) in summary_rec.items():
            lines.append(f"{name + ' (' + cls + ')':<40}{str(shapes):<30}{n:<12}")
            total += n
        lines.append("=" * 82)
        lines.append(f"Total params: {total}")
        print("\n".join(lines))

    def _flat_children(self, prefix=""):
        for name, child in self._children.items():
            path = f"{prefix}{name}"
            yield path, child
            yield from child._flat_children(path + ".")


class _HookHandle:
    def __init__(self, hooks_dict, hook_id):
        self._hooks_dict = hooks_dict
        self._id = hook_id

    def detach(self):
        self._hooks_dict.pop(self._id, None)


def _indent(s, num_spaces):
    lines = s.split("\n")
    if len(lines) == 1:
        return s
    first = lines.pop(0)
    return first + "\n" + "\n".join(" " * num_spaces + line for line in lines)


# ------------------------------------------------------------------- CachedOp
class _StagedHolder:
    """Per-(mode, structure) trace metadata captured during jit tracing."""

    __slots__ = ("fn", "n_out", "out_treedef", "aux_params", "last_flat",
                 "last_used")

    def __init__(self):
        self.fn = None
        self.n_out = None
        self.out_treedef = None
        self.aux_params = None
        self.last_flat = None  # avals of the most recent call (for export)
        self.last_used = 0  # global call sequence (export picks the newest)


def _is_nd(x):
    return isinstance(x, NDArray)


def _mode_summary(training, recording, flat_args):
    return (f"{'train' if training else 'eval'} "
            f"{'vjp' if recording else 'fwd'} "
            f"{_cc.aval_summary(flat_args)}")


class CachedOp:
    """Stages a Block's forward through ``jax.jit`` (reference:
    ``src/imperative/cached_op.cc``; ``static_alloc``/``static_shape`` map to
    XLA's buffer management and are accepted as no-ops)."""

    _call_seq = 0  # class-wide recency counter for export()

    def __init__(self, block: "HybridBlock", flags=()):
        self._block = block
        self._flags = dict(flags)
        self._param_list = None  # ordered [(name, Parameter)]
        self._staged = {}  # (training, in_treedef) -> _StagedHolder
        # each distinct (mode, structure, operand-aval) signature is one
        # compiled program; the guard counts them exactly and alarms on
        # post-warmup shape churn (compile_cache.RecompileGuard)
        self._guard = _cc.RecompileGuard(
            f"CachedOp({type(block).__name__})")

    def _collect(self):
        if self._param_list is None:
            self._param_list = list(self._block.collect_params().items())
        return self._param_list

    def _make_staged(self, training: bool, in_treedef):
        from .. import autograd

        holder = _StagedHolder()
        params = [p for _, p in self._collect()]
        n_params = len(params)
        block = self._block

        def staged(*flat):
            key = flat[-1]
            param_datas = flat[:n_params]
            input_datas = flat[n_params:-1]
            mapping = {p: NDArray(d) for p, d in zip(params, param_datas)}
            inputs, kwargs = jax.tree.unflatten(
                in_treedef, [NDArray(d) for d in input_datas]
            )
            sink = OrderedDict()
            with param_override(mapping), _random.key_supply(key), _aux_scope(
                sink
            ), _trace_scope(), autograd._scope(False, training):
                out = block.forward(*inputs, **kwargs)
            out_nds, out_tree = jax.tree.flatten(
                out, is_leaf=_is_nd
            )
            holder.out_treedef = out_tree
            holder.n_out = len(out_nds)
            holder.aux_params = list(sink.keys())
            flat_out = tuple(
                o.data if isinstance(o, NDArray) else jnp.asarray(o)
                for o in out_nds
            )
            return flat_out + tuple(sink[p] for p in holder.aux_params)

        holder.fn = jax.jit(staged)
        return holder

    def __call__(self, *inputs, **kwargs):
        from .. import autograd

        # kwargs ride the same pytree as positional inputs, so the staging
        # cache key (treedef) distinguishes e.g. valid_length present/absent
        input_nds, in_treedef = jax.tree.flatten(
            (inputs, dict(kwargs)), is_leaf=_is_nd
        )
        if not all(isinstance(i, NDArray) for i in input_nds):
            input_nds = [
                i if isinstance(i, NDArray) else NDArray(jnp.asarray(i))
                for i in input_nds
            ]
        training = autograd.is_training()
        cache_key = (training, in_treedef)
        holder = self._staged.get(cache_key)
        if holder is None:
            holder = self._make_staged(training, in_treedef)
            self._staged[cache_key] = holder
        params = [p for _, p in self._collect()]
        param_nds = [p.data() for p in params]
        key = _random.next_key()
        flat_args = [n.data for n in param_nds] + [n.data for n in input_nds] + [key]
        # export() serializes the shapes/signature actually in use: remember
        # ABSTRACT avals only — storing the live arrays would pin the most
        # recent batch's device buffers (HBM scales with batch size and
        # traced signatures) for the block's lifetime
        holder.last_flat = [
            jax.ShapeDtypeStruct(a.shape, a.dtype) for a in flat_args
        ]
        CachedOp._call_seq += 1
        holder.last_used = CachedOp._call_seq

        all_in_nds = param_nds + input_nds
        recording = autograd.is_recording() and any(
            autograd._is_tracked(a) for a in all_in_nds
        )
        # forward and recorded (vjp) dispatches compile distinct programs
        # — track them as distinct signatures
        self._guard.observe(
            (training, recording, in_treedef,
             tuple((a.shape, a.dtype.name) for a in flat_args)),
            lambda: _mode_summary(training, recording, flat_args))
        if recording:
            outs_flat, vjp_fn = jax.vjp(holder.fn, *flat_args)
            # untracked inputs (e.g. labels) and the PRNG key become None
            node_inputs = [
                a if autograd._is_tracked(a) else None for a in all_in_nds
            ] + [None]
            avals = [(o.shape, o.dtype) for o in outs_flat]
            node = autograd._Node(vjp_fn, node_inputs, avals, multi_out=True)
            out_nds = []
            for i, o in enumerate(outs_flat):
                ndo = NDArray(o)
                autograd._mark_output(ndo, node, i)
                out_nds.append(ndo)
        else:
            outs_flat = holder.fn(*flat_args)
            out_nds = [NDArray(o) for o in outs_flat]

        primary = out_nds[: holder.n_out]
        aux_vals = out_nds[holder.n_out :]
        for p_aux, val in zip(holder.aux_params, aux_vals):
            p_aux._data._rebind(val.data)
        return jax.tree.unflatten(holder.out_treedef, primary)

    # --------------------------------------------------------------- warmup
    def warmup(self, *example_sets, training=None, backward=False):
        """AOT-compile the staged program for each input signature.

        Each ``example_set`` is a sequence of per-input specs (positional
        inputs only) — an array, ``jax.ShapeDtypeStruct``, or ``(shape,
        dtype)`` pair::

            op.warmup((( (bs, key), "int32"),), (((bs2, key2), "int32"),))

        Runs the real jitted forward on zeros (parameters read, never
        written — aux state like BN running stats is NOT rebound), so the
        jit dispatch cache is hot and the first real call of each shape
        pays nothing. ``backward=True`` additionally compiles the
        recorded (vjp) program the autograd path uses. ``training``
        selects the staged mode and defaults to ``backward`` — a
        training loop records under train mode, so warm THAT program.
        Afterwards the guard is steady: new shapes count as
        ``compile/steady_state_recompiles`` (``MXTPU_RECOMPILE_LIMIT``).
        Returns the number of freshly compiled programs."""
        if training is None:
            training = bool(backward)
        compiled = 0
        reg = _tel.registry()
        for examples in example_sets:
            specs = [_cc.normalize_spec(s) for s in examples]
            inputs = tuple(NDArray(jnp.zeros(sh, dt)) for sh, dt in specs)
            input_nds, in_treedef = jax.tree.flatten(
                (inputs, {}), is_leaf=_is_nd)
            cache_key = (training, in_treedef)
            holder = self._staged.get(cache_key)
            if holder is None:
                holder = self._make_staged(training, in_treedef)
                self._staged[cache_key] = holder
            params = [p for _, p in self._collect()]
            key = _random.next_key()
            flat_args = [p.data().data for p in params] + \
                [n.data for n in input_nds] + [key]
            avals = tuple((a.shape, a.dtype.name) for a in flat_args)
            holder.last_flat = [
                jax.ShapeDtypeStruct(a.shape, a.dtype) for a in flat_args
            ]
            CachedOp._call_seq += 1
            holder.last_used = CachedOp._call_seq
            if self._guard.observe(
                    (training, False, in_treedef, avals),
                    lambda: _mode_summary(training, False, flat_args)):
                compiled += 1
                reg.counter("compile/warmup_compiles").inc()
                jax.block_until_ready(holder.fn(*flat_args))
            if backward and self._guard.observe(
                    (training, True, in_treedef, avals),
                    lambda: _mode_summary(training, True, flat_args)):
                compiled += 1
                reg.counter("compile/warmup_compiles").inc()
                outs, vjp_fn = jax.vjp(holder.fn, *flat_args)
                cts = tuple(jnp.zeros(o.shape, o.dtype) for o in outs)
                jax.block_until_ready(vjp_fn(cts))
        self._guard.mark_steady()
        return compiled

    def cache_info(self) -> dict:
        """Staged-cache summary: signatures held (one compiled program
        each), per-signature aval rendering, use counts, recency — plus
        the count of staged (mode, structure) holders."""
        info = self._guard.info()
        info["staged_programs"] = len(self._staged)
        return info


# ---------------------------------------------------------------- HybridBlock
class HybridBlock(Block):
    """A Block whose forward can be staged into one XLA program.

    Subclasses implement ``hybrid_forward(self, F, x, *args, **params)``
    where F is the op namespace and params are this block's registered
    parameters resolved to NDArrays (reference API preserved; F is always
    the ``nd`` namespace here since there is no symbolic mode)."""

    # Subclasses that are natural checkpoint boundaries (transformer /
    # BERT encoder+decoder layers in the model zoo) set this True:
    # ``hybridize(remat=policy)`` then wraps EACH such block's traced
    # application in its own ``jax.checkpoint`` region — per-layer
    # rematerialization, the memonger segmentation with layer boundaries
    # as the checkpoints (see ``mxnet_tpu.remat``).
    _remat_unit = False

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._flags = []
        self._cached_op = None
        self._remat_policy = None
        self._remat_active = False

    def __setattr__(self, name, value):
        super().__setattr__(name, value)
        if isinstance(value, HybridBlock):
            self._clear_cached_op()

    def _clear_cached_op(self):
        self._cached_op = None

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  inline_limit=2, forward_bulk_size=None,
                  backward_bulk_size=None, remat=None):
        self._active = active
        self._flags = [("static_alloc", static_alloc), ("static_shape", static_shape)]
        self._clear_cached_op()
        # per-layer rematerialization: the policy propagates to every
        # child but only arms blocks that declare themselves remat units
        # (``_remat_unit``) — each such block's traced application becomes
        # one jax.checkpoint region. remat=None leaves existing policies
        # untouched (hybridize(False) alone must not disarm a configured
        # net); remat=False/'off' explicitly disarms.
        if remat is not None:
            if remat in (False, "off", "0", "none"):
                self._remat_policy = None
            else:
                from .. import remat as _remat_mod

                _remat_mod.resolve_policy(remat)  # validate eagerly
                self._remat_policy = remat if type(self)._remat_unit \
                    else None
        # children run inside the parent's trace; still record their flags
        super().hybridize(
            active,
            static_alloc=static_alloc,
            static_shape=static_shape,
            inline_limit=inline_limit,
            remat=remat,
        )

    def cast(self, dtype):
        self._clear_cached_op()
        super().cast(dtype)

    def infer_shape(self, *args):
        """Resolve deferred parameter shapes from example inputs. Layers with
        deferred params override this; container blocks resolve via a
        shape-only abstract forward (``jax.eval_shape``)."""
        self._probe_shapes(*args)

    def _probe_shapes(self, *args):
        from .. import autograd

        def run(*datas):
            wrapped = jax.tree.unflatten(
                jax.tree.structure(args, is_leaf=_is_nd),
                [NDArray(d) for d in datas],
            )
            with autograd._scope(False, False), _trace_scope(), _probe_scope():
                self.forward(*wrapped)
            return jnp.zeros(())

        flat = [a.data for a in jax.tree.leaves(args, is_leaf=_is_nd)]
        jax.eval_shape(run, *flat)
        # shapes are now known; materialize for real OUTSIDE the trace
        for _, p in self.collect_params().items():
            p._finish_deferred_init()

    def _deferred_pending(self) -> bool:
        for _, p in self.collect_params().items():
            if p._data is None:
                return True
        return False

    def forward(self, x, *args, **kwargs):
        if self._active and not _in_trace():
            if not getattr(self, "_params_ready", False):
                if self._deferred_pending():
                    # probe with positional inputs only: optional kwargs
                    # (masks, lengths) never determine parameter shapes
                    self._probe_shapes(x, *args)
                object.__setattr__(self, "_params_ready", True)
            if self._cached_op is None:
                self._cached_op = CachedOp(self, self._flags)
            return self._cached_op(x, *args, **kwargs)
        if getattr(self, "_remat_policy", None) is not None \
                and not self._remat_active and _in_trace() \
                and not _in_probe():
            # armed remat unit inside a trace (TrainStep forward_loss or a
            # CachedOp staging): this block's application becomes one
            # jax.checkpoint region
            return self._call_with_remat(x, *args, **kwargs)
        # eager path (also the body that gets traced by CachedOp)
        try:
            params = {name: p.data() for name, p in self._reg_params.items()}
        except DeferredInitializationError:
            self.infer_shape(x, *args)
            if _in_probe():
                # shape probe: shapes resolved above; placeholders stand in
                # for the real arrays (created after the probe, untraced)
                params = {}
                for name, p in self._reg_params.items():
                    try:
                        params[name] = p.data()
                    except (DeferredInitializationError, MXNetError):
                        params[name] = NDArray(
                            jnp.zeros(tuple(p._shape), jnp.dtype(p._dtype))
                        )
            else:
                for p in self._reg_params.values():
                    p._finish_deferred_init()
                params = {
                    name: p.data() for name, p in self._reg_params.items()
                }
        return self.hybrid_forward(nd_namespace, x, *args, **kwargs, **params)

    def _call_with_remat(self, *args, **kwargs):
        """Apply this block as ONE ``jax.checkpoint`` region inside the
        enclosing trace (policy from ``hybridize(remat=...)``).

        The PRNG key is drawn from the ambient supply OUTSIDE the region
        and passed as an explicit operand: splitting inside the
        checkpointed trace would leak a tracer out of the region, and the
        backward's recompute must replay IDENTICAL dropout masks (same
        recipe as the hand-rolled BERTEncoder remat this generalizes).
        Parameters resolve inside via the ambient ``param_override`` and
        enter the region as closed-over tracers (new-style ``jax.remat``
        supports that). Aux-sink writers (BatchNorm) must not be remat
        units — their stat updates would escape the region."""
        from .. import random as _random
        from .. import remat as _remat_mod

        policy = _remat_mod.resolve_policy(self._remat_policy)
        supply = _random.current_key_supply()
        # outside a supply scope (pure-eval traces) a constant key is
        # fine: nothing stochastic can be live there
        key = supply.next() if supply is not None else jax.random.PRNGKey(0)
        flat, treedef = jax.tree.flatten((args, dict(kwargs)), is_leaf=_is_nd)
        datas = tuple(a.data if isinstance(a, NDArray) else jnp.asarray(a)
                      for a in flat)
        out_tree = []

        def fn(k, *ds):
            wrapped_args, wrapped_kwargs = jax.tree.unflatten(
                treedef, [NDArray(d) for d in ds])
            self._remat_active = True
            try:
                with _random.key_supply(k):
                    out = self.forward(*wrapped_args, **wrapped_kwargs)
            finally:
                self._remat_active = False
            leaves, tree = jax.tree.flatten(out, is_leaf=_is_nd)
            out_tree.append(tree)
            return tuple(
                o.data if isinstance(o, NDArray) else jnp.asarray(o)
                for o in leaves)

        outs = jax.checkpoint(fn, policy=policy)(key, *datas)
        return jax.tree.unflatten(out_tree[-1],
                                  [NDArray(o) for o in outs])

    def hybrid_forward(self, F, x, *args, **kwargs):  # pragma: no cover
        raise NotImplementedError

    # -------------------------------------------------------------- export
    def export(self, path, epoch=0):
        """Serialize the staged program + params for deployment (reference:
        ``HybridBlock.export`` -> model-symbol.json + model-0000.params).

        Writes ``{path}-symbol.json`` (manifest), ``{path}-{epoch:04d}.params``
        and ``{path}-symbol.stablehlo`` — the staged XLA program serialized
        via ``jax.export`` so ``SymbolBlock.imports`` can reconstruct a
        runnable forward with no Python model code (the TPU-native analogue
        of the reference's nnvm graph JSON)."""
        if not self._active or self._cached_op is None or not self._cached_op._staged:
            raise MXNetError(
                "run at least one forward after hybridize() before export"
            )
        params_file = f"{path}-{epoch:04d}.params"
        # single source of truth for the arg:/aux: classification — the
        # .params keys and the manifest's param_order must never diverge
        ordered = [
            (f"arg:{name}" if p.grad_req != "null" else f"aux:{name}", p)
            for name, p in self._cached_op._collect()
        ]
        from ..ndarray import save as nd_save

        nd_save(params_file, {key: p.data() for key, p in ordered})

        # the deployment artifact must be a predict-mode program (dropout
        # off, batchnorm in running-stats mode); among predict traces pick
        # the most recently called input signature
        staged = self._cached_op._staged
        predict = [h for k, h in staged.items() if not k[0]]
        if not predict:
            raise MXNetError(
                "export needs a predict-mode trace: run one forward outside "
                "autograd.record()/train_mode() before export()"
            )
        holder = max(predict, key=lambda h: h.last_used)
        in_avals = [
            jax.ShapeDtypeStruct(a.shape, a.dtype) for a in holder.last_flat
        ]
        hlo_file = f"{path}-symbol.stablehlo"
        from jax import export as jax_export

        exported = jax_export.export(holder.fn)(*in_avals)
        with open(hlo_file, "wb") as f:
            f.write(bytes(exported.serialize()))

        # manifest stores basenames: the artifact triple relocates as a unit
        meta = {
            "format": "mxnet_tpu-export-v1",
            "params": os.path.basename(params_file),
            "stablehlo": os.path.basename(hlo_file),
            "param_order": [key for key, _ in ordered],
            "param_names": [n for n, _ in self._cached_op._collect()],
            "n_out": holder.n_out,
            "n_inputs": len(in_avals) - len(ordered) - 1,
            "class": type(self).__name__,
            # the traced program's key operand layout depends on the PRNG
            # impl active at export (rbg: uint32[4], threefry: uint32[2]);
            # imports must rebuild the key with the SAME impl
            "prng_impl": jax.config.jax_default_prng_impl,
        }
        with open(f"{path}-symbol.json", "w") as f:
            json.dump(meta, f, indent=2)
        return f"{path}-symbol.json", params_file


class SymbolBlock(HybridBlock):
    """Load an exported model into a runnable forward (reference:
    ``SymbolBlock.imports`` over model-symbol.json [unverified]).

    The exported ``.stablehlo`` artifact (written by ``HybridBlock.export``)
    is deserialized via ``jax.export`` into a compiled callable; parameters
    come from the ``.params`` file in the manifest's recorded order. The
    result runs with no Python model code, like the reference's
    symbol-graph deployment path."""

    def __init__(self, outputs=None, inputs=None, params=None, meta=None):
        super().__init__(prefix="", params=None)
        self._fn = outputs  # callable(params_dict, *inputs) | Exported
        self._loaded = params or {}
        self._meta = meta or {}
        self._exported = None

    @staticmethod
    def imports(symbol_file, input_names=None, param_file=None, ctx=None):
        with open(symbol_file) as f:
            meta = json.load(f)
        if meta.get("format") != "mxnet_tpu-export-v1":
            raise MXNetError(f"unrecognized export format in {symbol_file}")
        from ..ndarray import load as nd_load

        # manifest paths resolve next to the manifest itself (basenames are
        # stored; any legacy path is reduced to its basename), so the
        # artifact triple relocates as a unit
        base = os.path.dirname(os.path.abspath(symbol_file))

        def _resolve(p):
            return os.path.join(base, os.path.basename(p))

        params = nd_load(param_file or _resolve(meta["params"]))
        blk = SymbolBlock(params=params, meta=meta)
        hlo_file = meta.get("stablehlo")
        hlo_file = _resolve(hlo_file) if hlo_file else None
        if hlo_file and os.path.exists(hlo_file):
            from jax import export as jax_export

            with open(hlo_file, "rb") as f:
                blk._exported = jax_export.deserialize(bytearray(f.read()))
        return blk

    def forward(self, *args):
        from ..ndarray.ndarray import NDArray as _ND

        if self._exported is not None:
            order = self._meta["param_order"]
            missing = [n for n in order if n not in self._loaded]
            if missing:
                raise MXNetError(f"params file missing entries: {missing}")
            flat = [self._loaded[n].data for n in order]
            # flatten nested input structures the same way the trace did
            in_leaves, _ = jax.tree.flatten(args, is_leaf=lambda x: isinstance(x, _ND))
            expect = self._meta.get("n_inputs")
            if expect is not None and len(in_leaves) != expect:
                raise MXNetError(
                    f"this exported model takes {expect} input array(s), "
                    f"got {len(in_leaves)}"
                )
            flat += [
                a.data if isinstance(a, _ND) else jnp.asarray(a)
                for a in in_leaves
            ]
            impl = self._meta.get("prng_impl")
            # predict-mode program; key layout must match the export-time
            # PRNG impl (recorded in the manifest since export-v1.1)
            flat.append(jax.random.PRNGKey(0, impl=impl) if impl
                        else jax.random.PRNGKey(0))
            outs = self._exported.call(*flat)
            outs = outs if isinstance(outs, (tuple, list)) else (outs,)
            primary = [_ND(o) for o in outs[: self._meta.get("n_out", len(outs))]]
            return primary[0] if len(primary) == 1 else tuple(primary)
        if self._fn is None:
            raise MXNetError(
                "this SymbolBlock holds parameters only; attach a forward "
                "callable or rebuild the model class and load_parameters"
            )
        return self._fn(self._loaded, *args)
