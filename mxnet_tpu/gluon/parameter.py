"""Parameter / ParameterDict (reference: ``python/mxnet/gluon/parameter.py``
[unverified]).

Structural difference from the reference: there are no per-device replica
copies (``_check_and_get`` ctx lists). A Parameter owns ONE NDArray; on TPU,
multi-device placement is a *sharding* of that one array over the mesh
(GSPMD), applied by ``mxnet_tpu.parallel`` — so ``list_data()`` returns a
single element and ``ctx`` arguments are accepted for compatibility.
"""

from __future__ import annotations

import re
import threading
from collections import OrderedDict
from typing import Optional

import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from ..context import Context, current_context
from ..ndarray.ndarray import NDArray
from .. import initializer

__all__ = ["DeferredInitializationError", "Parameter", "Constant", "ParameterDict"]


class DeferredInitializationError(MXNetError):
    """Parameter shape is not yet known; init is deferred to first forward."""


_PARAM_OVERRIDE = threading.local()


def _override_map():
    if not hasattr(_PARAM_OVERRIDE, "stack"):
        _PARAM_OVERRIDE.stack = []
    return _PARAM_OVERRIDE.stack


class param_override:
    """Scope mapping Parameter -> substitute NDArray (used by CachedOp tracing
    so staged forwards see traced parameter values, and by AMP for casts)."""

    def __init__(self, mapping):
        self._mapping = mapping

    def __enter__(self):
        _override_map().append(self._mapping)
        return self

    def __exit__(self, *exc):
        _override_map().pop()
        return False


class Parameter:
    """A weight/bias/aux tensor with lazy (possibly deferred) initialization.

    Parameters
    ----------
    name : str
    grad_req : {'write', 'add', 'null'}
    shape : tuple of int, 0 entries mean "infer at first forward"
    dtype : numpy dtype or str
    lr_mult / wd_mult : per-param hyper multipliers
    init : Initializer or str
    allow_deferred_init : allow shape to stay unknown until first forward
    differentiable : False for aux states (BatchNorm running stats)
    """

    def __init__(self, name, grad_req="write", shape=None, dtype=_np.float32,
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self._var = None
        self._data: Optional[NDArray] = None
        self._deferred_init = ()
        self._differentiable = differentiable
        if not differentiable:
            grad_req = "null"
        self._allow_deferred_init = allow_deferred_init
        self._grad_req = None
        self._shape = tuple(int(s) for s in shape) if shape is not None else None
        self.name = name
        self._dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.grad_req = grad_req
        self.init = init
        if stype != "default":
            raise MXNetError(
                "sparse parameter storage is not supported by the TPU build; "
                "use default stype"
            )
        if grad_stype not in ("default", "row_sparse"):
            raise MXNetError(
                f"unsupported grad_stype {grad_stype!r}; 'row_sparse' is "
                "the only sparse gradient storage (embedding gradients)"
            )
        self.grad_stype = grad_stype

    def __repr__(self):
        return f"Parameter {self.name} (shape={self.shape}, dtype={self.dtype})"

    # ------------------------------------------------------------ properties
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise MXNetError(f"grad_req must be write/add/null, got {req!r}")
        if not self._differentiable:
            req = "null"
        if self._grad_req == req:
            return
        self._grad_req = req
        if self._data is not None:
            if req == "null":
                self._data._grad = None
                self._data._grad_req = "null"
            else:
                self._init_grad()

    @property
    def dtype(self):
        return self._dtype

    @dtype.setter
    def dtype(self, dtype):
        self.cast(dtype)

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        if len(self._shape) != len(new_shape) or any(
            s != 0 and s != n for s, n in zip(self._shape, new_shape)
        ):
            raise MXNetError(
                f"cannot update shape of {self.name} from {self._shape} to {new_shape}"
            )
        self._shape = tuple(int(s) for s in new_shape)

    # ---------------------------------------------------------------- init
    def _shape_known(self):
        return self._shape is not None and all(s > 0 for s in self._shape)

    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        if self._data is not None and not force_reinit:
            return
        if default_init is None:
            default_init = initializer.Uniform()
        init = initializer.create(init) if init is not None else None
        if not self._shape_known():
            if not self._allow_deferred_init:
                raise MXNetError(
                    f"cannot initialize {self.name}: shape {self._shape} unknown "
                    "and allow_deferred_init=False"
                )
            self._deferred_init = (init, ctx, default_init)
            return
        self._finish_init(init, default_init)

    def _finish_init(self, init, default_init):
        data = NDArray(jnp.zeros(self._shape, jnp.dtype(self._dtype)))
        explicit = init if init is not None else (
            initializer.create(self.init) if self.init is not None else None
        )
        if explicit is not None:
            # an init chosen FOR this parameter bypasses the global init's
            # name-suffix dispatch (else bias_initializer='ones' would zero);
            # initializers with a custom __call__ (Mixed, Load, plain
            # callables) keep their own dispatch
            std_call = (
                isinstance(explicit, initializer.Initializer)
                and type(explicit).__call__ is initializer.Initializer.__call__
            )
            if std_call:
                explicit._init_default(initializer.InitDesc(self.name), data)
            else:
                explicit(initializer.InitDesc(self.name), data)
        else:
            default_init(initializer.InitDesc(self.name), data)
        self._data = data
        if self._grad_req != "null":
            self._init_grad()

    def _finish_deferred_init(self):
        if not self._deferred_init:
            return
        if not self._shape_known():
            raise DeferredInitializationError(
                f"parameter {self.name} has unknown shape {self._shape}"
            )
        init, _ctx, default_init = self._deferred_init
        self._deferred_init = ()
        self._finish_init(init, default_init)

    def _init_grad(self):
        from .. import autograd

        autograd._attach_grad(self._data, self._grad_req)

    # --------------------------------------------------------------- access
    def _check_and_get(self):
        for mapping in reversed(_override_map()):
            if self in mapping:
                return mapping[self]
        if self._data is not None:
            return self._data
        if self._deferred_init:
            raise DeferredInitializationError(
                f"parameter {self.name} has not been initialized yet: deferred "
                "init pending first forward"
            )
        raise MXNetError(
            f"parameter {self.name} has not been initialized; call "
            ".initialize() on the Block first"
        )

    def data(self, ctx=None) -> NDArray:
        return self._check_and_get()

    def list_data(self):
        return [self._check_and_get()]

    def grad(self, ctx=None) -> NDArray:
        d = self._check_and_get()
        if d._grad is None:
            raise MXNetError(
                f"cannot get gradient of {self.name}: grad_req='{self._grad_req}'"
            )
        return d._grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        return [self._check_and_get().ctx] if self._data is not None else [current_context()]

    def set_data(self, data):
        if isinstance(data, NDArray):
            data = data.data
        else:
            data = jnp.asarray(data)
        self.shape = data.shape
        if self._data is None:
            if self._deferred_init:
                self._finish_deferred_init()
            else:
                self._data = NDArray(jnp.zeros(self.shape, jnp.dtype(self._dtype)))
                if self._grad_req != "null":
                    self._init_grad()
        self._data._rebind(data.astype(self._data.data.dtype))

    def _aux_update(self, new_value):
        """Update a non-differentiable state (BatchNorm moving stats). Under
        CachedOp tracing the update is captured by the aux sink and applied
        after the jitted call; eagerly it rebinds in place."""
        from .block import _current_aux_sink

        sink = _current_aux_sink()
        if sink is not None:
            sink[self] = new_value if not isinstance(new_value, NDArray) else new_value.data
        else:
            self._data._rebind(
                new_value.data if isinstance(new_value, NDArray) else new_value
            )

    def zero_grad(self):
        if self._data is not None and self._data._grad is not None:
            self._data.zero_grad()

    def cast(self, dtype):
        self._dtype = dtype
        if self._data is not None:
            had_grad = self._data._grad is not None
            self._data._rebind(self._data.data.astype(jnp.dtype(dtype)))
            if had_grad:
                self._init_grad()

    def reset_ctx(self, ctx=None):
        pass  # single logical array; placement is a sharding concern

    def var(self):
        raise MXNetError("symbolic var() has no TPU-native equivalent; "
                         "hybridize() stages through jax.jit instead")

    def __reduce__(self):
        raise MXNetError("Parameter objects are not picklable; save/load "
                         "parameters through Block.save_parameters")


class Constant(Parameter):
    """Non-trainable constant (reference: ``gluon.Constant``)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = NDArray(jnp.asarray(value))
        self.value = value

        class _CInit(initializer.Initializer):
            def _init_weight(self, _, arr):
                arr._rebind(value.data)

            _init_default = _init_weight

        super().__init__(
            name, grad_req="null", shape=value.shape,
            dtype=str(value.data.dtype), init=_CInit(), differentiable=False
        )


class ParameterDict:
    """Ordered name->Parameter mapping with prefix and sharing (reference:
    ``gluon.ParameterDict``)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    def __repr__(self):
        s = "\n".join(f"  {v}" for v in self.values())
        return f"ParameterDict '{self._prefix}' (\n{s}\n)"

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    @property
    def prefix(self):
        return self._prefix

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def get(self, name, **kwargs) -> Parameter:
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if k == "shape" and v is not None:
                    param.shape = v
                elif k == "init" and v is not None and param.init is None:
                    param.init = v
                elif getattr(param, k, None) is None and v is not None:
                    setattr(param, k, v)
        return param

    def get_constant(self, name, value=None) -> Constant:
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise MXNetError(f"no constant named {name}; value required")
            param = Constant(name, value)
            self._params[name] = param
        return param

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError(f"cannot update self with other: duplicate key {k}")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        if init is None:
            init = initializer.Uniform()
        for _, v in self.items():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        pass

    def setattr(self, name, value):
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=""):
        from ..ndarray import save as nd_save

        arg_dict = {}
        for param in self.values():
            weight = param.data()
            if not param.name.startswith(strip_prefix):
                raise MXNetError(
                    f"prefix {strip_prefix} does not match parameter {param.name}"
                )
            arg_dict[param.name[len(strip_prefix):]] = weight
        nd_save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False, ignore_extra=False,
             restore_prefix="", cast_dtype=False, dtype_source="current"):
        from ..ndarray import load as nd_load

        loaded = nd_load(filename)
        arg_dict = {
            restore_prefix + k.replace("arg:", "").replace("aux:", ""): v
            for k, v in loaded.items()
        }
        if not allow_missing:
            for name in self.keys():
                if name not in arg_dict:
                    raise MXNetError(
                        f"parameter {name} missing in {filename}; set "
                        "allow_missing=True to skip"
                    )
        for name, val in arg_dict.items():
            if name not in self._params:
                if not ignore_extra:
                    raise MXNetError(
                        f"parameter {name} in file not in this dict; set "
                        "ignore_extra=True to skip"
                    )
                continue
            param = self._params[name]
            if cast_dtype and dtype_source == "current" and param._data is not None:
                val = val.astype(param.dtype)
            param.set_data(val)
