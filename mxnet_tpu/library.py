"""Runtime-loaded C++ operator extensions.

TPU-native analogue of the reference's custom-op extension ABI
(``include/mxnet/lib_api.h`` + ``mx.library.load`` [unverified]). The
reference dlopens a user .so exporting registration symbols and runs its
FCompute on CPU. Here the contract is a small C ABI (below); loaded ops are
registered in the SAME operator registry as built-ins, so they appear in
``mx.nd.*`` and work with autograd:

- host compute stays C++ (called through ctypes on numpy buffers);
- under ``jit``/``hybridize`` tracing the call lowers to
  ``jax.pure_callback`` (XLA host callback) wrapped in ``jax.custom_vjp``
  when the library exports a backward — the staged-graph path of the
  reference's CustomOp, XLA-style. (The tunneled axon TPU backend does not
  implement host callbacks; traced custom ops require the CPU platform or a
  real TPU runtime, and raise a clear error otherwise.)

C ABI (version 1 — elementwise contract: output shape == input[0] shape):

.. code-block:: c

    int  mxtpu_abi_version(void);              // must return 1
    int  mxtpu_op_count(void);
    const char* mxtpu_op_name(int op);
    int  mxtpu_op_num_inputs(int op);
    void mxtpu_op_compute(int op, const float** ins, const long long* lens,
                          int nin, float* out, long long out_len);
    int  mxtpu_op_has_backward(int op);        // optional, default 0
    // in-grad w.r.t. input 0 (reference CustomOp backward contract)
    void mxtpu_op_backward(int op, const float* out_grad, const float** ins,
                           const long long* lens, int nin, float* grad0,
                           long long len);

See ``examples/extensions/`` for a complete library + build line.
"""

from __future__ import annotations

import ctypes
from typing import List

import jax
import jax.numpy as jnp
import numpy as _np

from .base import MXNetError
from .ops import registry as _registry

__all__ = ["load"]

_LOADED: List[ctypes.CDLL] = []


def _compute_via_c(lib, op_id, nin):
    def compute(*arrays):
        ins = [
            _np.ascontiguousarray(_np.asarray(a, dtype=_np.float32))
            for a in arrays
        ]
        if len(ins) != nin:
            raise MXNetError(
                f"custom op expects {nin} inputs, got {len(ins)}"
            )
        out = _np.empty_like(ins[0])
        in_ptrs = (ctypes.POINTER(ctypes.c_float) * nin)(
            *[i.ctypes.data_as(ctypes.POINTER(ctypes.c_float)) for i in ins]
        )
        lens = (ctypes.c_longlong * nin)(*[i.size for i in ins])
        lib.mxtpu_op_compute(
            op_id, in_ptrs, lens, nin,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), out.size,
        )
        return out

    return compute


def _backward_via_c(lib, op_id, nin):
    def backward(out_grad, *arrays):
        og = _np.ascontiguousarray(_np.asarray(out_grad, _np.float32))
        ins = [
            _np.ascontiguousarray(_np.asarray(a, _np.float32))
            for a in arrays
        ]
        grad0 = _np.empty_like(ins[0])
        in_ptrs = (ctypes.POINTER(ctypes.c_float) * nin)(
            *[i.ctypes.data_as(ctypes.POINTER(ctypes.c_float)) for i in ins]
        )
        lens = (ctypes.c_longlong * nin)(*[i.size for i in ins])
        lib.mxtpu_op_backward(
            op_id, og.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            in_ptrs, lens, nin,
            grad0.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), grad0.size,
        )
        return grad0

    return backward


def _make_op_fn(name, compute, backward, nin):
    """Build the registry-level fn: numpy fast path eagerly, pure_callback
    (+ custom_vjp when backward exists) under tracing."""

    def _host_call(*arrays):
        out_aval = jax.ShapeDtypeStruct(
            jnp.shape(arrays[0]), jnp.float32
        )
        return jax.pure_callback(
            lambda *a: compute(*a), out_aval, *arrays, vmap_method="sequential"
        )

    if backward is not None:
        traced = jax.custom_vjp(_host_call)

        def fwd(*arrays):
            return _host_call(*arrays), arrays

        def bwd(res, ct):
            g_aval = jax.ShapeDtypeStruct(jnp.shape(res[0]), jnp.float32)
            g0 = jax.pure_callback(
                lambda ctg, *a: backward(ctg, *a), g_aval, ct, *res,
                vmap_method="sequential",
            )
            return (g0,) + tuple(None for _ in res[1:])

        traced.defvjp(fwd, bwd)
    else:
        traced = _host_call

    def fn(*arrays, **kw):
        if any(isinstance(a, jax.core.Tracer) for a in arrays):
            return traced(*arrays)
        # eager: straight to C++ on host buffers (reference FCompute-on-CPU)
        return jnp.asarray(compute(*[_np.asarray(a) for a in arrays]))

    fn.__name__ = name
    fn.__doc__ = f"Custom C++ operator ``{name}`` (loaded via mx.library.load)."
    return fn


def load(path, verbose=True):
    """dlopen an extension library and register its operators
    (reference: ``mx.library.load('libmyop.so')``)."""
    try:
        lib = ctypes.CDLL(path)
    except OSError as e:
        raise MXNetError(f"cannot load extension library {path!r}: {e}")
    for sym in ("mxtpu_abi_version", "mxtpu_op_count", "mxtpu_op_name",
                "mxtpu_op_num_inputs", "mxtpu_op_compute"):
        if not hasattr(lib, sym):
            raise MXNetError(
                f"{path}: missing required symbol {sym!r} (not an mxtpu "
                "extension library)"
            )
    lib.mxtpu_abi_version.restype = ctypes.c_int
    lib.mxtpu_op_count.restype = ctypes.c_int
    lib.mxtpu_op_name.restype = ctypes.c_char_p
    lib.mxtpu_op_name.argtypes = [ctypes.c_int]
    lib.mxtpu_op_num_inputs.restype = ctypes.c_int
    lib.mxtpu_op_num_inputs.argtypes = [ctypes.c_int]
    lib.mxtpu_op_compute.argtypes = [
        ctypes.c_int, ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_int,
        ctypes.POINTER(ctypes.c_float), ctypes.c_longlong,
    ]
    abi = lib.mxtpu_abi_version()
    if abi != 1:
        raise MXNetError(f"{path}: unsupported mxtpu ABI version {abi}")
    has_bwd_fn = getattr(lib, "mxtpu_op_has_backward", None)
    if has_bwd_fn is not None:
        has_bwd_fn.restype = ctypes.c_int
        has_bwd_fn.argtypes = [ctypes.c_int]
        lib.mxtpu_op_backward.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
            ctypes.POINTER(ctypes.c_longlong), ctypes.c_int,
            ctypes.POINTER(ctypes.c_float), ctypes.c_longlong,
        ]

    names = []
    for i in range(lib.mxtpu_op_count()):
        name = lib.mxtpu_op_name(i).decode()
        nin = lib.mxtpu_op_num_inputs(i)
        compute = _compute_via_c(lib, i, nin)
        backward = None
        if has_bwd_fn is not None and has_bwd_fn(i):
            backward = _backward_via_c(lib, i, nin)
        fn = _make_op_fn(name, compute, backward, nin)
        if _registry.maybe_get(name) is not None:
            raise MXNetError(
                f"{path}: operator {name!r} already registered"
            )
        _registry.register(
            name, differentiable=backward is not None
        )(fn)
        names.append(name)
    _LOADED.append(lib)  # keep the handle alive
    # refresh generated namespaces so mx.nd.<name> appears
    import sys

    from .ndarray import register as _nd_register

    _nd_register.populate_module(sys.modules["mxnet_tpu.ndarray"], "nd")
    if verbose:
        print(f"loaded library {path}: ops {names}")
    return names
