"""Runtime-loaded C++ operator extensions.

TPU-native analogue of the reference's custom-op extension ABI
(``include/mxnet/lib_api.h`` + ``mx.library.load`` [unverified]). The
reference dlopens a user .so exporting registration symbols and runs its
FCompute on CPU. Here the contract is a small C ABI (below); loaded ops are
registered in the SAME operator registry as built-ins, so they appear in
``mx.nd.*`` and work with autograd:

- host compute stays C++ (called through ctypes on numpy buffers);
- under ``jit``/``hybridize`` tracing the call lowers to
  ``jax.pure_callback`` (XLA host callback) wrapped in ``jax.custom_vjp``
  when the library exports a backward — the staged-graph path of the
  reference's CustomOp, XLA-style. (The tunneled axon TPU backend does not
  implement host callbacks; traced custom ops require the CPU platform or a
  real TPU runtime, and raise a clear error otherwise.)

C ABI version 1 (elementwise contract: output shape == input[0] shape):

.. code-block:: c

    int  mxtpu_abi_version(void);              // must return 1
    int  mxtpu_op_count(void);
    const char* mxtpu_op_name(int op);
    int  mxtpu_op_num_inputs(int op);
    void mxtpu_op_compute(int op, const float** ins, const long long* lens,
                          int nin, float* out, long long out_len);
    int  mxtpu_op_has_backward(int op);        // optional, default 0
    // in-grad w.r.t. input 0 (reference CustomOp backward contract)
    void mxtpu_op_backward(int op, const float* out_grad, const float** ins,
                           const long long* lens, int nin, float* grad0,
                           long long len);

C ABI version 2 (``mxtpu_abi_version() == 2`` — the full lib_api.h
contract: per-op shape/dtype inference, multi-output, non-f32 dtypes,
scalar params as a "k=v;k=v" string):

.. code-block:: c

    // dtype codes: 0=f32 1=f64 2=i32 3=i64 4=u8 5=bool
    int  mxtpu_op_num_outputs(int op);
    // writes out_ndims/out_shapes (row-major, max_ndim per output) and
    // out_dtypes given the input signature; returns 0 on success
    int  mxtpu_op_infer(int op, const long long* in_shapes,
                        const int* in_ndims, const int* in_dtypes, int nin,
                        long long* out_shapes, int* out_ndims,
                        int* out_dtypes, int max_ndim, const char* params);
    void mxtpu_op_compute2(int op, const void** ins,
                           const long long* in_shapes, const int* in_ndims,
                           const int* in_dtypes, int nin, void** outs,
                           const long long* out_shapes, const int* out_ndims,
                           const int* out_dtypes, int nout,
                           const char* params);
    int  mxtpu_op_has_backward(int op);        // optional
    // grads for EVERY input (same signature layout; integer inputs get
    // zero-filled buffers the library may ignore)
    void mxtpu_op_backward2(int op, const void** out_grads, const void** ins,
                            const long long* in_shapes, const int* in_ndims,
                            const int* in_dtypes, int nin, void** in_grads,
                            const char* params);

Both versions load through the same ``mx.library.load``. For users
without a C++ toolchain, the pure-Python ``mx.operator.CustomOp`` path
(``mxnet_tpu/operator.py``) offers the same hook — the reference's
``custom.cc`` callback operator.

See ``examples/extensions/`` for complete libraries + build lines.
"""

from __future__ import annotations

import ctypes
from typing import List

import jax
import jax.numpy as jnp
import numpy as _np

from .base import MXNetError
from .ops import registry as _registry

__all__ = ["load"]

_LOADED: List[ctypes.CDLL] = []


def _compute_via_c(lib, op_id, nin):
    def compute(*arrays):
        ins = [
            _np.ascontiguousarray(_np.asarray(a, dtype=_np.float32))
            for a in arrays
        ]
        if len(ins) != nin:
            raise MXNetError(
                f"custom op expects {nin} inputs, got {len(ins)}"
            )
        out = _np.empty_like(ins[0])
        in_ptrs = (ctypes.POINTER(ctypes.c_float) * nin)(
            *[i.ctypes.data_as(ctypes.POINTER(ctypes.c_float)) for i in ins]
        )
        lens = (ctypes.c_longlong * nin)(*[i.size for i in ins])
        lib.mxtpu_op_compute(
            op_id, in_ptrs, lens, nin,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), out.size,
        )
        return out

    return compute


def _backward_via_c(lib, op_id, nin):
    def backward(out_grad, *arrays):
        og = _np.ascontiguousarray(_np.asarray(out_grad, _np.float32))
        ins = [
            _np.ascontiguousarray(_np.asarray(a, _np.float32))
            for a in arrays
        ]
        grad0 = _np.empty_like(ins[0])
        in_ptrs = (ctypes.POINTER(ctypes.c_float) * nin)(
            *[i.ctypes.data_as(ctypes.POINTER(ctypes.c_float)) for i in ins]
        )
        lens = (ctypes.c_longlong * nin)(*[i.size for i in ins])
        lib.mxtpu_op_backward(
            op_id, og.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            in_ptrs, lens, nin,
            grad0.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), grad0.size,
        )
        return grad0

    return backward


def _make_op_fn(name, compute, backward, nin):
    """Build the registry-level fn: numpy fast path eagerly, pure_callback
    (+ custom_vjp when backward exists) under tracing."""

    def _host_call(*arrays):
        out_aval = jax.ShapeDtypeStruct(
            jnp.shape(arrays[0]), jnp.float32
        )
        return jax.pure_callback(
            lambda *a: compute(*a), out_aval, *arrays, vmap_method="sequential"
        )

    if backward is not None:
        traced = jax.custom_vjp(_host_call)

        def fwd(*arrays):
            return _host_call(*arrays), arrays

        def bwd(res, ct):
            g_aval = jax.ShapeDtypeStruct(jnp.shape(res[0]), jnp.float32)
            g0 = jax.pure_callback(
                lambda ctg, *a: backward(ctg, *a), g_aval, ct, *res,
                vmap_method="sequential",
            )
            return (g0,) + tuple(None for _ in res[1:])

        traced.defvjp(fwd, bwd)
    else:
        traced = _host_call

    def fn(*arrays, **kw):
        if any(isinstance(a, jax.core.Tracer) for a in arrays):
            return traced(*arrays)
        # eager: straight to C++ on host buffers (reference FCompute-on-CPU)
        return jnp.asarray(compute(*[_np.asarray(a) for a in arrays]))

    fn.__name__ = name
    fn.__doc__ = f"Custom C++ operator ``{name}`` (loaded via mx.library.load)."
    return fn


# numpy dtype <-> ABI v2 dtype code
_DTYPES = [_np.float32, _np.float64, _np.int32, _np.int64, _np.uint8,
           _np.bool_]


def _dtype_code(dt) -> int:
    dt = _np.dtype(dt)
    for i, d in enumerate(_DTYPES):
        if dt == _np.dtype(d):
            return i
    raise MXNetError(f"unsupported extension dtype {dt}")


def _params_str(kw: dict) -> bytes:
    return ";".join(f"{k}={v}" for k, v in sorted(kw.items())).encode()


_MAX_NDIM = 8


def _make_v2_compute(lib, op_id, nin, nout):
    def compute(*arrays, **kw):
        ins = [_np.ascontiguousarray(a) for a in arrays]
        if len(ins) != nin:
            raise MXNetError(
                f"custom op expects {nin} inputs, got {len(ins)}"
            )
        params = _params_str(kw)
        in_shapes = (ctypes.c_longlong * (nin * _MAX_NDIM))()
        in_ndims = (ctypes.c_int * nin)()
        in_dtypes = (ctypes.c_int * nin)()
        for i, a in enumerate(ins):
            in_ndims[i] = a.ndim
            in_dtypes[i] = _dtype_code(a.dtype)
            for d, s in enumerate(a.shape):
                in_shapes[i * _MAX_NDIM + d] = s
        out_shapes = (ctypes.c_longlong * (nout * _MAX_NDIM))()
        out_ndims = (ctypes.c_int * nout)()
        out_dtypes = (ctypes.c_int * nout)()
        rc = lib.mxtpu_op_infer(op_id, in_shapes, in_ndims, in_dtypes, nin,
                                out_shapes, out_ndims, out_dtypes,
                                _MAX_NDIM, params)
        if rc != 0:
            raise MXNetError(f"custom op infer failed (rc={rc})")
        outs = []
        for o in range(nout):
            shape = tuple(out_shapes[o * _MAX_NDIM + d]
                          for d in range(out_ndims[o]))
            outs.append(_np.empty(shape, _DTYPES[out_dtypes[o]]))
        in_ptrs = (ctypes.c_void_p * nin)(
            *[a.ctypes.data_as(ctypes.c_void_p) for a in ins])
        out_ptrs = (ctypes.c_void_p * nout)(
            *[o.ctypes.data_as(ctypes.c_void_p) for o in outs])
        lib.mxtpu_op_compute2(op_id, in_ptrs, in_shapes, in_ndims,
                              in_dtypes, nin, out_ptrs, out_shapes,
                              out_ndims, out_dtypes, nout, params)
        return outs[0] if nout == 1 else tuple(outs)

    return compute


def _make_v2_backward(lib, op_id, nin, nout):
    def backward(out_grads, ins_np, **kw):
        params = _params_str(kw)
        ins = [_np.ascontiguousarray(a) for a in ins_np]
        ogs = [_np.ascontiguousarray(g) for g in out_grads]
        in_shapes = (ctypes.c_longlong * (nin * _MAX_NDIM))()
        in_ndims = (ctypes.c_int * nin)()
        in_dtypes = (ctypes.c_int * nin)()
        for i, a in enumerate(ins):
            in_ndims[i] = a.ndim
            in_dtypes[i] = _dtype_code(a.dtype)
            for d, s in enumerate(a.shape):
                in_shapes[i * _MAX_NDIM + d] = s
        grads = [_np.zeros_like(a) for a in ins]
        og_ptrs = (ctypes.c_void_p * nout)(
            *[g.ctypes.data_as(ctypes.c_void_p) for g in ogs])
        in_ptrs = (ctypes.c_void_p * nin)(
            *[a.ctypes.data_as(ctypes.c_void_p) for a in ins])
        g_ptrs = (ctypes.c_void_p * nin)(
            *[g.ctypes.data_as(ctypes.c_void_p) for g in grads])
        lib.mxtpu_op_backward2(op_id, og_ptrs, in_ptrs, in_shapes,
                               in_ndims, in_dtypes, nin, g_ptrs, params)
        return grads

    return backward


def _make_v2_op_fn(name, compute, backward, nin, nout):
    """Registry fn for a v2 op (self_recording): receives the caller's
    NDArrays, runs the C++ body on host numpy, and registers its own
    tape entry when the lib exports a backward — eager only (the v2
    contract's dynamic output shapes can't stage through pure_callback
    without a host-side infer pass)."""
    from . import autograd as _ag
    from .ndarray.ndarray import NDArray

    def fn(*arrays, **kw):
        if any(isinstance(a, jax.core.Tracer) for a in arrays):
            raise MXNetError(
                f"custom op {name!r} (ABI v2) supports eager execution "
                "only; call outside jit/hybridize"
            )
        in_nds = [a if isinstance(a, NDArray) else NDArray(jnp.asarray(a))
                  for a in arrays]
        np_in = [a.asnumpy() for a in in_nds]
        out = compute(*np_in, **kw)
        if backward is None or not _ag.is_recording():
            if isinstance(out, tuple):
                return tuple(jnp.asarray(o) for o in out)
            return jnp.asarray(out)

        class _Fn(_ag.Function):
            def forward(self, *ins):
                o = out
                if isinstance(o, tuple):
                    return tuple(NDArray(jnp.asarray(x)) for x in o)
                return NDArray(jnp.asarray(o))

            def backward(self, *ogs):
                gs = backward([_np.asarray(g.data) for g in ogs],
                              np_in, **kw)
                return tuple(NDArray(jnp.asarray(g)) for g in gs)

        return _Fn()(*in_nds)

    fn.__name__ = name
    fn.__doc__ = (
        f"Custom C++ operator ``{name}`` (ABI v2: shape/dtype inference, "
        f"{nout} output(s), scalar params)."
    )
    return fn


def load(path, verbose=True):
    """dlopen an extension library and register its operators
    (reference: ``mx.library.load('libmyop.so')``)."""
    try:
        lib = ctypes.CDLL(path)
    except OSError as e:
        raise MXNetError(f"cannot load extension library {path!r}: {e}")
    for sym in ("mxtpu_abi_version", "mxtpu_op_count", "mxtpu_op_name",
                "mxtpu_op_num_inputs"):
        if not hasattr(lib, sym):
            raise MXNetError(
                f"{path}: missing required symbol {sym!r} (not an mxtpu "
                "extension library)"
            )
    lib.mxtpu_abi_version.restype = ctypes.c_int
    lib.mxtpu_op_count.restype = ctypes.c_int
    lib.mxtpu_op_name.restype = ctypes.c_char_p
    lib.mxtpu_op_name.argtypes = [ctypes.c_int]
    lib.mxtpu_op_num_inputs.restype = ctypes.c_int
    lib.mxtpu_op_num_inputs.argtypes = [ctypes.c_int]
    abi = lib.mxtpu_abi_version()
    if abi == 2:
        for sym in ("mxtpu_op_num_outputs", "mxtpu_op_infer",
                    "mxtpu_op_compute2"):
            if not hasattr(lib, sym):
                raise MXNetError(
                    f"{path}: ABI v2 library missing required symbol "
                    f"{sym!r}"
                )
        return _load_v2(path, lib, verbose)
    if abi != 1:
        raise MXNetError(f"{path}: unsupported mxtpu ABI version {abi}")
    if not hasattr(lib, "mxtpu_op_compute"):
        raise MXNetError(
            f"{path}: ABI v1 library missing required symbol "
            "'mxtpu_op_compute'"
        )
    lib.mxtpu_op_compute.argtypes = [
        ctypes.c_int, ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_int,
        ctypes.POINTER(ctypes.c_float), ctypes.c_longlong,
    ]
    has_bwd_fn = getattr(lib, "mxtpu_op_has_backward", None)
    if has_bwd_fn is not None:
        has_bwd_fn.restype = ctypes.c_int
        has_bwd_fn.argtypes = [ctypes.c_int]
        lib.mxtpu_op_backward.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
            ctypes.POINTER(ctypes.c_longlong), ctypes.c_int,
            ctypes.POINTER(ctypes.c_float), ctypes.c_longlong,
        ]

    names = []
    for i in range(lib.mxtpu_op_count()):
        name = lib.mxtpu_op_name(i).decode()
        nin = lib.mxtpu_op_num_inputs(i)
        compute = _compute_via_c(lib, i, nin)
        backward = None
        if has_bwd_fn is not None and has_bwd_fn(i):
            backward = _backward_via_c(lib, i, nin)
        fn = _make_op_fn(name, compute, backward, nin)
        if _registry.maybe_get(name) is not None:
            raise MXNetError(
                f"{path}: operator {name!r} already registered"
            )
        _registry.register(
            name, differentiable=backward is not None
        )(fn)
        names.append(name)
    _LOADED.append(lib)  # keep the handle alive
    # refresh generated namespaces so mx.nd.<name> appears
    import sys

    from .ndarray import register as _nd_register

    _nd_register.populate_module(sys.modules["mxnet_tpu.ndarray"], "nd")
    if verbose:
        print(f"loaded library {path}: ops {names}")
    return names


def _load_v2(path, lib, verbose):
    lib.mxtpu_op_num_outputs.restype = ctypes.c_int
    lib.mxtpu_op_num_outputs.argtypes = [ctypes.c_int]
    lib.mxtpu_op_infer.restype = ctypes.c_int
    lib.mxtpu_op_infer.argtypes = [
        ctypes.c_int, ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.c_int, ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.c_int, ctypes.c_char_p,
    ]
    lib.mxtpu_op_compute2.argtypes = [
        ctypes.c_int, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.c_int, ctypes.c_char_p,
    ]
    has_bwd_fn = getattr(lib, "mxtpu_op_has_backward", None)
    if has_bwd_fn is not None:
        has_bwd_fn.restype = ctypes.c_int
        has_bwd_fn.argtypes = [ctypes.c_int]
        lib.mxtpu_op_backward2.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.c_int,
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_char_p,
        ]

    names = []
    for i in range(lib.mxtpu_op_count()):
        name = lib.mxtpu_op_name(i).decode()
        nin = lib.mxtpu_op_num_inputs(i)
        nout = lib.mxtpu_op_num_outputs(i)
        compute = _make_v2_compute(lib, i, nin, nout)
        backward = None
        if has_bwd_fn is not None and has_bwd_fn(i):
            backward = _make_v2_backward(lib, i, nin, nout)
        fn = _make_v2_op_fn(name, compute, backward, nin, nout)
        if _registry.maybe_get(name) is not None:
            raise MXNetError(f"{path}: operator {name!r} already registered")
        # differentiable=False: the fn manages its own tape entry (the
        # Function above); the invoke layer's jax.vjp routing would hand
        # it tracers the host C++ cannot consume
        _registry.register(name, num_outputs=nout, differentiable=False,
                           self_recording=True)(fn)
        names.append(name)
    _LOADED.append(lib)
    import sys

    from .ndarray import register as _nd_register

    _nd_register.populate_module(sys.modules["mxnet_tpu.ndarray"], "nd")
    if verbose:
        print(f"loaded library {path} (ABI v2): ops {names}")
    return names
