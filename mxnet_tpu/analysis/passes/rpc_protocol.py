"""rpc-protocol pass: the worker wire protocol checked as data.

The verb table is EXTRACTED from ``serving/worker.py``'s ``RpcServer``
dispatch dict, the reply schemas from every ``respond(...)`` reachable
from each handler (following ``respond`` through self-calls and
``threading.Thread(target=self.X, args=(..., respond))`` relay threads),
and the consumption side from every ``RpcClient.call``/``submit`` site
in the serving plane — then the two sides are checked against each
other:

- **orphan-verb** — a production call site sends a verb no handler
  serves (the error surfaces at runtime as an ``unknown verb`` frame).
- **dead-verb** — a handler no caller anywhere (serving, tools,
  benchmarks, tests) exercises: dead protocol surface.
- **missing-reply-key** — a caller subscripts/``get``\\ s a key the
  handler never responds (including reads through a stored probe dict,
  e.g. ``self._probe_info``). The ``submit`` stream's consumer is the
  transport's ``_route``, so its reads come from there. The converse
  direction — keys responded but never read — is computed (``unread``)
  for tests/tools but NOT reported: ack fields (``pushed``,
  ``drained``...) are deliberate wire documentation.
- **missing-timeout** — a ``.call(...)`` site with no ``timeout_s=``
  whose receiver does not resolve to a client class carrying a default
  timeout (``self.timeout_s`` in ``__init__``): a hung peer would hang
  the caller forever.
- **unreachable-fault** — every verb must be reachable from a fault
  point: the shared ``transport.send``/``transport.recv`` pair on the
  frame path, or a verb-specific one (``transport.kv_push``) —
  otherwise the chaos suite cannot kill it, so its failure path is
  untested by construction.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import AnalysisPass, register
from .. import ast_driver as _ad
from .. import callgraph as _cg

SERVER_MODULES = (
    "mxnet_tpu/serving/worker.py",
    "mxnet_tpu/serving/transport.py",
)
CLIENT_MODULES = (
    "mxnet_tpu/serving/worker.py",
    "mxnet_tpu/serving/transport.py",
    "mxnet_tpu/serving/remote.py",
    "mxnet_tpu/serving/router.py",
    "mxnet_tpu/serving/watcher.py",
    "mxnet_tpu/serving/disagg.py",
    "mxnet_tpu/serving/tracing.py",
    "tools/launch.py",
)

# frame-envelope keys owned by the transport, not the verb payloads
PROTOCOL_KEYS = frozenset({"id", "ok", "done", "error", "nbin", "verb"})


def _dict_str_keys(d: ast.Dict) -> Optional[Set[str]]:
    keys = set()
    for k in d.keys:
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            keys.add(k.value)
        else:
            return None  # **spread or computed key: open schema
    return keys


def _verb_table(classes, rel_set):
    """{verb: (owner class, handler method, path, line)} from every
    ``RpcServer({...})`` dict-literal construction in the module set."""
    verbs = {}
    for cname, model in classes.items():
        if model.module.path not in rel_set:
            continue
        for mname, (fn, mod) in model.methods.items():
            for call in (n for n in ast.walk(fn)
                         if isinstance(n, ast.Call)):
                d = _ad.dotted(call.func) or ""
                if d.rsplit(".", 1)[-1] != "RpcServer" or not call.args \
                        or not isinstance(call.args[0], ast.Dict):
                    continue
                for k, v in zip(call.args[0].keys, call.args[0].values):
                    if not (isinstance(k, ast.Constant)
                            and isinstance(k.value, str)):
                        continue
                    h = _ad.self_attr(v)
                    if h is not None and h in model.methods:
                        verbs[k.value] = (cname, h, mod.path, k.lineno)
    return verbs


def _respond_keys(model, start) -> Optional[Set[str]]:
    """Reply keys a handler (and the self-calls / relay threads it hands
    ``respond`` to) can send; None = open schema (``respond(**opaque)``)."""
    keys: Set[str] = set()
    seen: Set[str] = set()
    stack = [start]
    while stack:
        mname = stack.pop()
        if mname in seen:
            continue
        seen.add(mname)
        fn = model.method(mname)
        if fn is None:
            continue
        local_dicts = {}
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name) and \
                    isinstance(n.value, ast.Dict):
                local_dicts[n.targets[0].id] = n.value
        for call in (n for n in ast.walk(fn) if isinstance(n, ast.Call)):
            f = call.func
            if isinstance(f, ast.Name) and f.id == "respond":
                for kw in call.keywords:
                    if kw.arg is not None:
                        keys.add(kw.arg)
                        continue
                    v = kw.value
                    if isinstance(v, ast.Name) and v.id in local_dicts:
                        v = local_dicts[v.id]
                    got = _dict_str_keys(v) \
                        if isinstance(v, ast.Dict) else None
                    if got is None:
                        return None
                    keys |= got
                continue
            # forwarding: respond handed to a self-call or relay thread
            mentions = any(
                isinstance(n, ast.Name) and n.id == "respond"
                for a in (list(call.args)
                          + [kw.value for kw in call.keywords])
                for n in ast.walk(a))
            if not mentions:
                continue
            cand = _ad.self_attr(f)
            if cand is not None and cand in model.methods:
                stack.append(cand)
            tgt = _cg.kwarg(call, "target")
            t = _ad.self_attr(tgt) if tgt is not None else None
            if t is not None and t in model.methods:
                stack.append(t)
    return keys


def _route_reads(classes) -> Set[str]:
    """Keys the transport's response router reads from reply frames —
    the consumer of the ``submit`` verb's stream."""
    out: Set[str] = set()
    model = classes.get("RpcClient")
    fn = model.method("_route") if model is not None else None
    if fn is None:
        return out
    args = fn.args.args
    msg = args[1].arg if len(args) > 1 else None
    if msg is None:
        return out
    for key, _ln in _reads_of_name(fn, msg):
        out.add(key)
    return out - PROTOCOL_KEYS


def _reads_of_name(fn, name) -> List[Tuple[str, int]]:
    """String-keyed reads of local ``name``: ``name["k"]`` and
    ``name.get("k", ...)``."""
    out = []
    for n in ast.walk(fn):
        if isinstance(n, ast.Subscript) and \
                isinstance(n.value, ast.Name) and n.value.id == name and \
                isinstance(n.slice, ast.Constant) and \
                isinstance(n.slice.value, str):
            out.append((n.slice.value, n.lineno))
        elif isinstance(n, ast.Call) and \
                isinstance(n.func, ast.Attribute) and \
                n.func.attr == "get" and \
                isinstance(n.func.value, ast.Name) and \
                n.func.value.id == name:
            k = _cg.str_arg(n)
            if k is not None:
                out.append((k, n.lineno))
    return out


def _attr_reads(model) -> Dict[str, List[Tuple[str, int]]]:
    """Class-wide string-keyed reads of ``self.X`` dicts (the stored
    health-probe pattern: ``self._probe_info.get("queue_depth")``)."""
    out: Dict[str, List[Tuple[str, int]]] = {}
    for mname, (fn, _mod) in model.methods.items():
        for n in ast.walk(fn):
            if isinstance(n, ast.Subscript) and \
                    isinstance(n.slice, ast.Constant) and \
                    isinstance(n.slice.value, str):
                attr = _ad.self_attr(n.value)
                if attr is not None:
                    out.setdefault(attr, []).append(
                        (n.slice.value, n.lineno))
            elif isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "get":
                attr = _ad.self_attr(n.func.value)
                k = _cg.str_arg(n)
                if attr is not None and k is not None:
                    out.setdefault(attr, []).append((k, n.lineno))
    return out


def _timeout_ok(types, owner, call) -> bool:
    if _cg.kwarg(call, "timeout_s") is not None or \
            _cg.kwarg(call, "timeout") is not None:
        return True
    recv = call.func.value
    t = types.expr_class(owner, recv)
    if t is not None:
        model = types.classes.get(t)
        init = model.method("__init__") if model is not None else None
        if init is not None:
            for n in ast.walk(init):
                if isinstance(n, ast.Assign) and any(
                        _ad.self_attr(tg) == "timeout_s"
                        for tg in n.targets):
                    return True
        return False
    # unresolved receiver: trust the repo's naming convention — RPC
    # clients are held in attrs/properties named *client
    name = (_cg.receiver_name(recv) or "").split(".")[-1]
    return name.endswith("client")


def _send_sites(graph, rel_set):
    """Every verb send in the client scope:
    (verb, path, line, where, timeout_ok, ast.Call)."""
    out = []
    for key, node in graph.nodes.items():
        if node.module.path not in rel_set:
            continue
        owner = key[0] if key[0] in graph.classes else None
        if owner == "RpcClient":
            continue  # the protocol plumbing itself
        for call in node.info.calls():
            f = call.func
            if not isinstance(f, ast.Attribute):
                continue
            if f.attr == "call":
                verb = _cg.str_arg(call)
                if verb is None:
                    continue
                out.append((verb, node.module.path, call.lineno,
                            f"{key[0]}.{key[1]}",
                            _timeout_ok(graph.types, owner, call), call))
            elif f.attr == "submit":
                t = graph.types.expr_class(owner, f.value)
                name = (_cg.receiver_name(f.value) or "").split(".")[-1]
                if t == "RpcClient" or name.endswith("client"):
                    out.append(("submit", node.module.path, call.lineno,
                                f"{key[0]}.{key[1]}", True, call))
    return out


def _reads_for_sends(graph, node, sends_in_fn, class_attr_reads):
    """Reply keys each verb-send's result is read for, within the
    sending function — through a local binding and through a stored
    ``self.X = result`` dict."""
    fn = graph.nodes[node].fn if isinstance(node, tuple) else node
    reads: Dict[str, List[Tuple[str, int]]] = {}
    calls_by_id = {id(c): v for v, c in sends_in_fn}
    for n in ast.walk(fn):
        if not isinstance(n, ast.Assign) or len(n.targets) != 1:
            continue
        if id(n.value) in calls_by_id and \
                isinstance(n.targets[0], ast.Name):
            verb = calls_by_id[id(n.value)]
            local = n.targets[0].id
            for key, ln in _reads_of_name(fn, local):
                reads.setdefault(verb, []).append((key, ln))
            # stored result: self.X = local -> class-wide reads of X
            for m in ast.walk(fn):
                if isinstance(m, ast.Assign) and \
                        isinstance(m.value, ast.Name) and \
                        m.value.id == local:
                    for tg in m.targets:
                        attr = _ad.self_attr(tg)
                        if attr is not None:
                            for key, ln in class_attr_reads.get(attr, []):
                                reads.setdefault(verb, []).append(
                                    (key, ln))
    # direct subscript on the call result: X.call("v")["k"]
    for n in ast.walk(fn):
        if isinstance(n, ast.Subscript) and id(n.value) in calls_by_id \
                and isinstance(n.slice, ast.Constant) \
                and isinstance(n.slice.value, str):
            reads.setdefault(calls_by_id[id(n.value)], []).append(
                (n.slice.value, n.lineno))
    return reads


def _fault_points(index, rel_paths) -> Set[str]:
    fires = set()
    for p in rel_paths:
        mod = index.module(p)
        for n in ast.walk(mod.tree):
            if isinstance(n, ast.Call):
                d = _ad.dotted(n.func) or ""
                if d.endswith("faults.fire") or d == "_faults.fire" or \
                        (isinstance(n.func, ast.Attribute)
                         and n.func.attr == "fire"
                         and "fault" in d):
                    tag = _cg.str_arg(n)
                    if tag is not None:
                        fires.add(tag)
    return fires


def _verbs_sent_in(index, rel_paths) -> Set[str]:
    """Verbs sent anywhere in extra module sets (tests/benchmarks) —
    the liveness scan for dead-verb."""
    out = set()
    for p in rel_paths:
        try:
            mod = index.module(p)
        except (OSError, SyntaxError):
            continue
        for n in ast.walk(mod.tree):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr in ("call", "submit"):
                v = _cg.str_arg(n) if n.func.attr == "call" else "submit"
                if v is not None:
                    out.add(v)
    return out


def analyze(index: _ad.AstIndex, server_paths=SERVER_MODULES,
            client_paths=CLIENT_MODULES, liveness_paths=()):
    """Cross-check the protocol; returns a dict of facts + violations
    (the seeded-control entry point)."""
    all_paths = list(dict.fromkeys(list(server_paths)
                                   + list(client_paths)))
    graph = _cg.ProjectGraph(index, all_paths)
    server_set = set(server_paths)
    client_set = set(client_paths)

    verbs = _verb_table(graph.classes, server_set)
    responds: Dict[str, Optional[Set[str]]] = {}
    for verb, (cname, handler, _p, _ln) in verbs.items():
        responds[verb] = _respond_keys(graph.classes[cname], handler)

    sends = _send_sites(graph, client_set)
    route_reads = _route_reads(graph.classes)

    # reads per verb, attributed to concrete sites
    reads: Dict[str, List[Tuple[str, str, int]]] = {}
    per_fn: Dict[_cg.NodeKey, list] = {}
    for verb, path, line, where, tok, call in sends:
        key = tuple(where.split(".", 1))
        per_fn.setdefault(key, []).append((verb, call))
    attr_reads_cache: Dict[str, Dict] = {}
    for key, pairs in per_fn.items():
        node = graph.nodes.get(key)
        if node is None:
            continue
        owner = key[0]
        if owner in graph.classes and owner not in attr_reads_cache:
            attr_reads_cache[owner] = _attr_reads(graph.classes[owner])
        got = _reads_for_sends(graph, node.fn, pairs,
                               attr_reads_cache.get(owner, {}))
        for verb, pairs2 in got.items():
            for k, ln in pairs2:
                reads.setdefault(verb, []).append(
                    (k, node.module.path, ln))
    if "submit" in {v for v, *_ in sends} or "submit" in verbs:
        for k in sorted(route_reads):
            reads.setdefault("submit", []).append(
                (k, "mxnet_tpu/serving/transport.py", 0))

    sent_verbs = {v for v, *_ in sends}
    live_verbs = sent_verbs | _verbs_sent_in(index, liveness_paths)
    fires = _fault_points(index, all_paths)

    orphans = [(v, p, ln, where) for v, p, ln, where, _t, _c in sends
               if v not in verbs]
    dead = sorted(v for v in verbs if v not in live_verbs)
    missing_timeout = [(v, p, ln, where)
                       for v, p, ln, where, tok, _c in sends if not tok]
    missing_reply = []
    unread: Dict[str, List[str]] = {}
    for verb, keys in responds.items():
        got = {k for k, _p, _ln in reads.get(verb, [])} - PROTOCOL_KEYS
        if keys is None:
            continue  # open schema: nothing to prove
        for k, p, ln in reads.get(verb, []):
            if k not in keys and k not in PROTOCOL_KEYS:
                missing_reply.append((verb, k, p, ln))
        extra = sorted(keys - got - PROTOCOL_KEYS)
        if extra:
            unread[verb] = extra
    transport_faults = {"transport.send", "transport.recv"} <= fires
    unreachable_fault = sorted(
        v for v in verbs
        if not transport_faults and f"transport.{v}" not in fires)

    return {
        "verbs": verbs, "responds": responds, "reads": reads,
        "sends": [(v, p, ln, where, tok)
                  for v, p, ln, where, tok, _c in sends],
        "orphans": orphans, "dead": dead,
        "missing_reply": missing_reply, "unread": unread,
        "missing_timeout": missing_timeout,
        "unreachable_fault": unreachable_fault, "fault_points": fires,
    }


@register
class RpcProtocolPass(AnalysisPass):
    name = "rpc-protocol"
    ir = "ast"
    description = ("worker verb table vs every call site: handlers "
                   "exist, reply keys cover reads, timeouts everywhere, "
                   "fault-point reachability")

    def run(self, ctx):
        facts = analyze(
            ctx.ast,
            liveness_paths=tuple(ctx.ast.package_files("tests",
                                                       "benchmarks")))
        findings = []
        table_path = next(iter(facts["verbs"].values()))[2] \
            if facts["verbs"] else SERVER_MODULES[0]
        for verb, path, ln, where in facts["orphans"]:
            findings.append(self.finding(
                "orphan-verb", path, ln, key=f"{where}:{verb}",
                message=f"{where} sends verb {verb!r} but no RpcServer "
                        f"handler serves it"))
        for verb in facts["dead"]:
            _c, _h, path, ln = facts["verbs"][verb]
            findings.append(self.finding(
                "dead-verb", path, ln, key=verb,
                message=f"verb {verb!r} has a handler but no caller "
                        f"anywhere (serving plane, tools, benchmarks, "
                        f"tests): dead protocol surface"))
        for verb, k, path, ln in facts["missing_reply"]:
            findings.append(self.finding(
                "missing-reply-key", path, ln, key=f"{verb}:{k}",
                message=f"a {verb!r} caller reads reply key {k!r} that "
                        f"the handler never responds — schema drift"))
        for verb, path, ln, where in facts["missing_timeout"]:
            findings.append(self.finding(
                "missing-timeout", path, ln, key=f"{where}:{verb}",
                message=f"{where} sends {verb!r} with no timeout_s= and "
                        f"no client-default timeout: a hung peer hangs "
                        f"the caller forever"))
        for verb in facts["unreachable_fault"]:
            findings.append(self.finding(
                "unreachable-fault", table_path, 1, key=verb,
                message=f"verb {verb!r} is not reachable from any fault "
                        f"point (transport.send/recv or its own): its "
                        f"failure path cannot be chaos-tested"))
        return findings
