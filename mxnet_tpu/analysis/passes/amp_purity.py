"""amp-purity pass: mixed precision must stay pure end to end.

Port of ``tools/check_amp_purity.py`` (PR 4) onto the pass framework —
same two checks, same assertions:

1. **jaxpr — no fp32 master feeds a low-precision dot.** Walks the real
   ``TrainStep(amp='bfloat16')`` program (shared ``ProgramIndex`` build)
   recursing into pjit/scan/cond/remat sub-jaxprs; any ``dot_general``
   mixing float32 with bfloat16/float16 operands means a master weight
   (or an un-downcast activation) reached an MXU op without its cast.
   Also asserts the program DOES contain low-precision dots at all — an
   all-f32 "amp" program means the cast pass silently stopped engaging.
2. **AST — no host sync in the overflow-skip path.** The fp16
   loss-scaling contract is that overflow steps cost no host round trip:
   walks ``TrainStep._build``'s traced closures and flags blocking calls
   (the no-sync rule set).
"""

from __future__ import annotations

import ast
import os

from ..core import AnalysisPass, REPO_ROOT, register
from .no_sync import STEP_PY, blocking_calls_in
from .. import jaxpr_driver as _jd


def check_step_purity(step=None, jaxpr=None):
    """Violation messages for the jaxpr check; builds the tiny step if
    neither a step nor a pre-lowered jaxpr is given."""
    import jax

    if jaxpr is None:
        if step is None:
            step = _jd.build_train_step()
        jaxpr = jax.make_jaxpr(step._step_fn)(*step._last_avals)
    mixed = [f"dot_general with operands {dts} — fp32 feeds a "
             f"low-precision dot without a cast" for _, dts in
             _jd.find_mixed_dots(jaxpr)]
    if _jd.count_low_precision_dots(jaxpr) == 0:
        mixed.append(
            "amp step program contains NO low-precision dot_general at "
            "all — the cast pass is not engaging")
    return mixed


def find_overflow_sync_violations(path=None):
    """Blocking host calls inside the TRACED closures of
    ``TrainStep._build`` (``step_core``/``forward_loss``/... — the step
    body XLA compiles, including the fp16 overflow-skip path).
    ``_build``'s own top-level statements run once on host at build time
    and may legitimately coerce hyperparameters."""
    if path is None:
        path = os.path.join(REPO_ROOT, STEP_PY)
    elif not os.path.isabs(path):
        path = os.path.join(REPO_ROOT, path)
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    classes = [n for n in tree.body
               if isinstance(n, ast.ClassDef) and n.name == "TrainStep"]
    if not classes:
        return [(0, f"TrainStep class not found in {path}")]
    builds = [n for n in classes[0].body
              if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
              and n.name == "_build"]
    if not builds:
        return [(classes[0].lineno, "_build method not found — update "
                 "the amp-purity pass if the builder was renamed")]
    out = []
    for fn in ast.walk(builds[0]):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                fn is not builds[0]:
            for lineno, msg in blocking_calls_in(fn, "_build"):
                out.append((lineno, msg.replace(
                    "blocks on the device value",
                    "would sync the overflow-skip path")))
    return sorted(set(out))


@register
class AmpPurityPass(AnalysisPass):
    name = "amp-purity"
    ir = "jaxpr"
    description = ("no fp32 master feeds a low-precision dot; the "
                   "overflow-skip path is sync-free")

    def run(self, ctx):
        findings = []
        for lineno, msg in find_overflow_sync_violations():
            findings.append(self.finding(
                "overflow-sync", STEP_PY, lineno, key=msg[:80],
                message=msg))
        for i, msg in enumerate(check_step_purity(
                jaxpr=ctx.programs.train_jaxpr)):
            findings.append(self.finding(
                "mixed-dot", STEP_PY, 0, key=f"jaxpr:{msg[:60]}",
                message="amp jaxpr: " + msg))
        return findings
