"""telemetry-names pass: every metric/span family emitted anywhere in
the package must be KNOWN to ``tools/telemetry_report.py``.

The report tool is the one place operators look; a metric emitted under
a family the tool has never heard of silently vanishes from every
report (the PR-1..PR-8 family sections each had to remember to add
themselves). The tool now declares its registry
(``KNOWN_METRIC_FAMILIES`` / ``KNOWN_SPAN_FAMILIES``) and this pass
closes the loop:

- any ``counter("x/...")``/``gauge``/``histogram`` emission whose family
  ``x`` is not in ``KNOWN_METRIC_FAMILIES`` is an orphan;
- any ``span("y....")``/``instant`` emission whose family ``y`` is not
  in ``KNOWN_SPAN_FAMILIES`` is an orphan;
- any family the tool declares but nothing emits is dead registry.

Only literal names are collected (f-string families are already pinned
by their literal prefix elsewhere or out of scope by construction).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from ..core import AnalysisPass, register
from .. import ast_driver as _ad

REPORT_TOOL = "tools/telemetry_report.py"
SCAN_DIRS = ("mxnet_tpu", "tools", "benchmarks")

METRIC_EMITTERS = {"counter", "gauge", "histogram"}
SPAN_EMITTERS = {"span", "instant"}


def collect_emissions(index: _ad.AstIndex):
    """(metric_families, span_families): family -> [(path, line, name)]."""
    metrics: Dict[str, List] = {}
    spans: Dict[str, List] = {}
    for rel in index.package_files(*SCAN_DIRS):
        if rel == REPORT_TOOL:
            continue
        try:
            mod = index.module(rel)
        except SyntaxError:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            first = node.args[0]
            if not isinstance(first, ast.Constant) or \
                    not isinstance(first.value, str):
                continue
            attr = getattr(node.func, "attr", None) or \
                getattr(node.func, "id", None)
            if attr in METRIC_EMITTERS and "/" in first.value:
                fam = first.value.split("/")[0]
                metrics.setdefault(fam, []).append(
                    (rel, node.lineno, first.value))
            elif attr in SPAN_EMITTERS and "." in first.value:
                fam = first.value.split(".")[0]
                spans.setdefault(fam, []).append(
                    (rel, node.lineno, first.value))
    return metrics, spans


def declared_families(index: _ad.AstIndex) -> Tuple[Set[str], Set[str],
                                                    Dict[str, int]]:
    """Families the report tool declares, parsed from its AST (the tool
    is a script, not an importable package module)."""
    mod = index.module(REPORT_TOOL)
    out = {"KNOWN_METRIC_FAMILIES": set(), "KNOWN_SPAN_FAMILIES": set()}
    lines: Dict[str, int] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id in out:
                v = node.value
                keys = []
                if isinstance(v, ast.Dict):
                    keys = v.keys
                elif isinstance(v, (ast.Set, ast.List, ast.Tuple)):
                    keys = v.elts
                for k in keys:
                    if isinstance(k, ast.Constant) and \
                            isinstance(k.value, str):
                        out[t.id].add(k.value)
                        lines[k.value] = k.lineno
    return (out["KNOWN_METRIC_FAMILIES"], out["KNOWN_SPAN_FAMILIES"],
            lines)


@register
class TelemetryNamesPass(AnalysisPass):
    name = "telemetry-names"
    ir = "meta"
    description = ("every emitted metric/span family is known to "
                   "tools/telemetry_report.py (and none is dead)")

    def run(self, ctx):
        findings = []
        metrics, spans = collect_emissions(ctx.ast)
        known_m, known_s, decl_lines = declared_families(ctx.ast)
        if not known_m:
            return [self.finding(
                "registry-missing", REPORT_TOOL, 0, key="KNOWN_FAMILIES",
                message=f"{REPORT_TOOL} declares no "
                "KNOWN_METRIC_FAMILIES — the report tool lost its "
                "family registry")]
        for fam, sites in sorted(metrics.items()):
            if fam not in known_m:
                path, ln, name = sites[0]
                findings.append(self.finding(
                    "orphan-metric", path, ln, key=f"metric:{fam}",
                    message=f"metric family {fam}/ (e.g. {name!r} at "
                    f"{path}:{ln}) is emitted but unknown to "
                    f"{REPORT_TOOL} — it vanishes from every report"))
        for fam, sites in sorted(spans.items()):
            if fam not in known_s:
                path, ln, name = sites[0]
                findings.append(self.finding(
                    "orphan-span", path, ln, key=f"span:{fam}",
                    message=f"span family {fam}.* (e.g. {name!r} at "
                    f"{path}:{ln}) is emitted but unknown to "
                    f"{REPORT_TOOL}"))
        for fam in sorted(known_m - set(metrics)):
            findings.append(self.finding(
                "dead-family", REPORT_TOOL, decl_lines.get(fam, 0),
                key=f"dead-metric:{fam}",
                message=f"metric family {fam}/ is declared in "
                f"{REPORT_TOOL} but nothing emits it"))
        for fam in sorted(known_s - set(spans)):
            findings.append(self.finding(
                "dead-family", REPORT_TOOL, decl_lines.get(fam, 0),
                key=f"dead-span:{fam}",
                message=f"span family {fam}.* is declared in "
                f"{REPORT_TOOL} but nothing emits it"))
        return findings
