"""donation pass: every big carried buffer is donated, every donation is
real, and host code never touches a buffer it gave away.

Donation is the memory contract the whole training/serving design leans
on (one extra copy of params+moments+KV pools is exactly the HBM the
batch planner thinks it has), and XLA fails soft when it breaks: an
undonated carry silently doubles peak memory; a donated-but-unaliasable
buffer is a warning in a log nobody reads; host code reading a donated
array dies later with a cryptic "buffer was deleted" — or worse, reads a
stale copy on backends that snapshot. Three rules:

- **contract** (AST) — every ``jax.jit(..., donate_argnums=...)`` in
  ``step.py``/``infer.py`` donates exactly the carried-state parameters
  by NAME: ``train_vals``/``opt_state``/``key``/``t`` (+
  ``scaler_state`` when the variant carries it) for the train step,
  ``state`` (the KV cache / paged pools) for the decode programs — and
  never donates non-carried inputs (``batch``/``label``/
  ``frozen_vals``/``src``). Conditional ``donate = () if cpu else
  (1,)`` resolves to the non-empty branch (the CPU test backend cannot
  alias; the contract is about the real backend).
- **aliasable** (jaxpr) — on the REAL lowered programs: each donated
  leaf is consumed by the program, and (for programs that return their
  carry) its aval appears among the outputs so XLA can actually alias
  it. A donated-but-unaliasable buffer is a silent no-op donation.
- **use-after-donate** (AST dataflow) — in the serving scheduler
  (``serving/batcher.py``), an argument passed into a donating engine
  call (``decode_iter``/``prefill_paged`` donate their ``state``) must
  be rebound from the call's result and never read again beforehand.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..core import AnalysisPass, register
from .. import ast_driver as _ad

STEP_PY = "mxnet_tpu/parallel/step.py"
INFER_PY = "mxnet_tpu/parallel/infer.py"
BATCHER_PY = "mxnet_tpu/serving/batcher.py"

# parameter names that ARE the carried state (must be donated)...
REQUIRED_STEP = {"train_vals", "opt_state", "key", "t", "scaler_state"}
REQUIRED_INFER = {"state"}
# ...and names that must NOT be (inputs read elsewhere / shared params)
FORBIDDEN = {"batch", "label", "frozen_vals", "src", "vl", "values",
             "page_tables", "tokens", "lengths", "active", "prime"}

# serving-side donating calls: callee attr -> donated positional index
DONATING_CALLS = {"decode_iter": 0, "prefill_paged": 0,
                  "prefill_suffix_paged": 0, "spec_draft": 0,
                  "spec_verify": 0}


def _literal_tuple(node) -> Optional[Tuple[int, ...]]:
    if isinstance(node, ast.Tuple) and all(
            isinstance(e, ast.Constant) and isinstance(e.value, int)
            for e in node.elts):
        return tuple(e.value for e in node.elts)
    return None


def _resolve_donate_expr(expr, fn) -> Optional[Tuple[int, ...]]:
    """Resolve a ``donate_argnums`` value: literal tuple, conditional
    ``X if c else Y`` (non-empty branch wins — the donation contract is
    about the real backend), or a Name assigned one of those in ``fn``."""
    lit = _literal_tuple(expr)
    if lit is not None:
        return lit
    if isinstance(expr, ast.IfExp):
        a = _resolve_donate_expr(expr.body, fn)
        b = _resolve_donate_expr(expr.orelse, fn)
        return a if a else b
    if isinstance(expr, ast.Name):
        best = None
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == expr.id
                    for t in node.targets):
                got = _resolve_donate_expr(node.value, fn)
                if got:
                    best = got
        return best
    return None


def jit_donation_sites(module: _ad.Module) -> List[dict]:
    """Every ``jax.jit(F, donate_argnums=...)`` in the module with the
    donated PARAMETER NAMES resolved: [{fn, lineno, donated,
    candidates}] where candidates is a list of possible parameter-name
    lists (same-named defs in one builder — e.g. the grad-accum
    variants — cannot be disambiguated statically, so the contract
    check accepts a site if ANY candidate satisfies it)."""
    out = []
    # enclosing (outermost) function for each call, for Name resolution
    enclosing: Dict[int, ast.FunctionDef] = {}
    top_fns = []
    for fn in ast.walk(module.tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            top_fns.append(fn)
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    enclosing.setdefault(id(node), fn)
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call) or \
                _ad.dotted(node.func) != "jax.jit":
            continue
        target = node.args[0] if node.args else None
        tname = target.id if isinstance(target, ast.Name) else None
        outer = enclosing.get(id(node))
        # candidate defs: same-name functions nested in the enclosing
        # builder first, falling back to anywhere in the module
        candidates = []
        if tname is not None and outer is not None:
            candidates = [n for n in ast.walk(outer)
                          if isinstance(n, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))
                          and n.name == tname]
        if tname is not None and not candidates:
            candidates = [n for n in top_fns if n.name == tname]
        donate = ()
        for kw in node.keywords:
            if kw.arg == "donate_argnums":
                donate = _resolve_donate_expr(kw.value, outer) or ()
        out.append({"fn": tname, "lineno": node.lineno, "donated": donate,
                    "candidates": [[a.arg for a in c.args.args]
                                   for c in candidates]})
    return out


def _contract_violations(params, donated, required, fn_name, lineno):
    """Violations for ONE candidate parameter list (empty = clean)."""
    names = {params[i] for i in donated if i < len(params)}
    out = []
    present_required = required & set(params)
    missing = present_required - names
    if missing:
        out.append((
            lineno, f"{fn_name}:missing:{sorted(missing)}",
            f"jitted {fn_name}({', '.join(params)}) does not donate "
            f"carried state {sorted(missing)} — peak memory silently "
            "doubles for those buffers"))
    bad = names & FORBIDDEN
    if bad:
        out.append((
            lineno, f"{fn_name}:forbidden:{sorted(bad)}",
            f"jitted {fn_name} donates {sorted(bad)} — these are "
            "shared/read-again inputs, donating them frees buffers "
            "the host still uses"))
    return out


def check_contract(module: _ad.Module, required, path) -> List[Tuple]:
    """[(lineno, key, message)] contract violations for one module. A
    site with several same-named candidate defs passes if ANY candidate
    satisfies the contract."""
    out = []
    sites = [s for s in jit_donation_sites(module) if s["candidates"]]
    if not sites:
        return [(0, f"{path}:no-jit",
                 f"{path}: no jax.jit sites with resolvable functions "
                 "found — the donation contract has nothing to check "
                 "(update the pass if the builder moved)")]
    for s in sites:
        per_candidate = [
            _contract_violations(params, s["donated"], required,
                                 s["fn"], s["lineno"])
            for params in s["candidates"]
            if required & set(params)]
        if per_candidate and all(per_candidate):
            out.extend(per_candidate[0])
    return out


# ------------------------------------------------------------ jaxpr checks
def _flatten_positions(args):
    import jax

    spans = []
    start = 0
    for a in args:
        leaves = jax.tree.flatten(a)[0]
        spans.append((start, start + len(leaves)))
        start += len(leaves)
    return spans, start


def check_aliasable(closed_jaxpr, example_args, donated_positions,
                    label, require_output_alias=True) -> List[str]:
    """Each donated leaf must be consumed by the program; when the
    program returns its carry, each donated leaf's aval must also appear
    among the outputs (else XLA cannot alias and the donation is a
    silent no-op)."""
    jaxpr = closed_jaxpr.jaxpr
    spans, total = _flatten_positions(example_args)
    if total != len(jaxpr.invars):
        return [f"{label}: example args flatten to {total} leaves but "
                f"the jaxpr has {len(jaxpr.invars)} invars — the "
                "donation map is stale"]
    used = set()
    from .. import jaxpr_driver as _jd

    for eqn in _jd.iter_eqns(closed_jaxpr):
        for v in eqn.invars:
            used.add(id(v))
    out_avals = {}
    for v in jaxpr.outvars:
        a = getattr(v, "aval", None)
        if a is not None and hasattr(a, "shape"):
            k = (tuple(a.shape), str(a.dtype))
            out_avals[k] = out_avals.get(k, 0) + 1
    msgs = []
    for pos in donated_positions:
        lo, hi = spans[pos]
        for v in jaxpr.invars[lo:hi]:
            a = v.aval
            if id(v) not in used and v not in jaxpr.outvars:
                msgs.append(
                    f"{label}: donated leaf {a.shape}/{a.dtype} (arg "
                    f"{pos}) is never consumed by the program — dead "
                    "donation, likely a stale argnum")
                continue
            if require_output_alias:
                k = (tuple(a.shape), str(a.dtype))
                if out_avals.get(k, 0) > 0:
                    out_avals[k] -= 1
                else:
                    msgs.append(
                        f"{label}: donated leaf {a.shape}/{a.dtype} "
                        f"(arg {pos}) matches NO output aval — XLA "
                        "cannot alias it; the donation is a no-op and "
                        "the buffer is simply destroyed")
    return msgs


def run_jaxpr_checks(programs) -> List[str]:
    import inspect

    msgs = []
    step = programs.train_step
    try:
        params = list(inspect.signature(step._step_fn).parameters)
    except (TypeError, ValueError):
        params = []
    if params and set(params) & REQUIRED_STEP:
        donated = [i for i, p in enumerate(params) if p in REQUIRED_STEP]
    else:
        # jit wrapper hides the signature: fall back to the known step
        # layout (train_vals, frozen, opt, batch, label, key, lr, t,
        # rescale[, scaler_state])
        donated = [0, 2, 5, 7] + (
            [9] if len(step._last_avals) == 10 else [])
    msgs += check_aliasable(programs.train_jaxpr, step._last_avals,
                            donated, "TrainStep")
    _, decode_jaxpr, _, decode_args = programs.decode_programs()
    msgs += check_aliasable(decode_jaxpr, decode_args, [1],
                            "InferStep.decode",
                            require_output_alias=False)
    pj, dj, pargs, dargs = programs.paged_programs()
    msgs += check_aliasable(pj, pargs, [1], "InferStep.prefill_paged")
    msgs += check_aliasable(dj, dargs, [1], "InferStep.decode_iter")
    return msgs


# --------------------------------------------------- use-after-donate AST
def check_use_after_donate(module: _ad.Module,
                           donating=DONATING_CALLS) -> List[Tuple]:
    """[(lineno, key, message)]: donated args read after the donating
    call, or never rebound from its result."""
    out = []
    for cls in module.classes.values():
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            out.extend(_check_fn(cls.name, fn, donating))
    return out


def _donating_call_in(stmt, donating):
    for node in ast.walk(stmt):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in donating:
            pos = donating[node.func.attr]
            if pos < len(node.args):
                key = _ad.dotted(node.args[pos])
                if key is not None:
                    return node, key
    return None, None


def _assign_targets(stmt):
    keys = set()
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    for t in targets:
        stack = [t]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.Tuple, ast.List)):
                stack.extend(n.elts)
            elif isinstance(n, ast.Starred):
                stack.append(n.value)
            else:
                k = _ad.dotted(n)
                if k is not None:
                    keys.add(k)
    return keys


_COMPOUND = (ast.For, ast.AsyncFor, ast.While, ast.If, ast.With,
             ast.AsyncWith, ast.Try, ast.FunctionDef, ast.AsyncFunctionDef,
             ast.ClassDef)


def _check_fn(cls_name, fn, donating):
    out = []
    # only SIMPLE statements participate: compound containers are
    # containers — their nested simple statements are walked separately
    # (a For holding the donating call must not shadow the per-statement
    # rebind analysis of its body)
    stmts = sorted((s for s in _ad.walk_statements(fn.body)
                    if not isinstance(s, _COMPOUND)),
                   key=lambda s: s.lineno)
    pending = None  # (key, call_lineno, callee)
    for stmt in stmts:
        if pending is not None:
            key, call_ln, callee = pending
            reads = [n for n in ast.walk(stmt)
                     if isinstance(n.ctx if hasattr(n, "ctx") else None,
                                   ast.Load) and _ad.dotted(n) == key]
            rebinds = key in _assign_targets(stmt)
            if reads and not rebinds:
                out.append((
                    stmt.lineno,
                    f"{cls_name}.{fn.name}:{key}:use-after",
                    f"{cls_name}.{fn.name} reads {key} at line "
                    f"{stmt.lineno} AFTER donating it into "
                    f"{callee}(...) at line {call_ln} — the buffer is "
                    "deleted (or stale) once the dispatch consumes it"))
                pending = None
                continue
            if rebinds:
                pending = None
        node, key = _donating_call_in(stmt, donating)
        if node is not None:
            if key in _assign_targets(stmt):
                continue  # rebound in the same statement — the pattern
            pending = (key, node.lineno, node.func.attr)
    if pending is not None:
        key, call_ln, callee = pending
        out.append((
            call_ln, f"{cls_name}.{fn.name}:{key}:lost",
            f"{cls_name}.{fn.name} donates {key} into {callee}(...) at "
            f"line {call_ln} but never rebinds it from the result — the "
            "live carry is lost and the next dispatch reuses a deleted "
            "buffer"))
    return out


@register
class DonationPass(AnalysisPass):
    name = "donation"
    ir = "jaxpr"
    description = ("donate_argnums cover the carried state, donations "
                   "are consumed+aliasable, no host use-after-donate")

    def run(self, ctx):
        findings = []
        for path, required in ((STEP_PY, REQUIRED_STEP),
                               (INFER_PY, REQUIRED_INFER)):
            mod = ctx.ast.module(path)
            for ln, key, msg in check_contract(mod, required, path):
                findings.append(self.finding("contract", path, ln,
                                             key=key, message=msg))
        for ln, key, msg in check_use_after_donate(
                ctx.ast.module(BATCHER_PY)):
            findings.append(self.finding("use-after-donate", BATCHER_PY,
                                         ln, key=key, message=msg))
        for msg in run_jaxpr_checks(ctx.programs):
            findings.append(self.finding(
                "aliasable", STEP_PY, 0, key=msg[:80], message=msg))
        return findings
