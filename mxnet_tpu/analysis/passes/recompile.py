"""recompile-hazard pass: nothing in the hot paths may churn traced
signatures.

PR 3 made execution shape-stable (bucketing + AOT warmup + the
RecompileGuard); this pass guards the invariants that keep it that way:

- **cfg-hygiene** (AST) — the host-side config normalizers
  (``_decode_cfg``/``_paged_cfg``/any ``*_cfg``) key the jitted-program
  caches; a ``float(...)`` element (e.g. temperature) would mint a new
  program per VALUE. Config keys must stay int/str. Temperature & co.
  belong in TRACED operands (``jnp.float32(x)``), not cache keys.
- **traced-shape-branch** (AST) — an ``if``/``while`` on ``.shape`` /
  ``len(...)`` inside a traced closure (the functions built in
  ``_build``/``_build_forward``/``_get_*_fn``) silently compiles a
  different program per shape variant; shape policy belongs in the
  bucketing layer. Host entropy (``time.*``/``random.*``) inside a
  traced closure is baked in at trace time — also flagged.
- **guard-accounting** (AST) — every dispatch method that fetches a
  jitted program (``self._get_*_fn``/``self._fwd_fn``/
  ``self._step_fn``) must route through ``compile_guard.observe`` first;
  an unaccounted dispatch is invisible to the recompile alarm.
- **guard-crosscheck** (runtime) — drive the REAL engine twice with
  identical shapes but different Python scalar knobs (temperature):
  the RecompileGuard signature count and the jitted-program cache must
  not grow — the executable cross-check that the two AST rules stay
  honest.
"""

from __future__ import annotations

import ast
from typing import List, Tuple

from ..core import AnalysisPass, register
from .. import ast_driver as _ad

STEP_PY = "mxnet_tpu/parallel/step.py"
INFER_PY = "mxnet_tpu/parallel/infer.py"

# builders whose NESTED functions are traced closures
TRACED_BUILDERS = {
    STEP_PY: ("_build",),
    INFER_PY: ("_build_forward", "_get_prefill_fn", "_get_decode_fn",
               "_get_paged_prefill_fn", "_get_decode_iter_fn",
               "_get_suffix_fn", "_get_spec_draft_fn",
               "_get_spec_verify_fn"),
}

# dispatch methods that must account their signatures with the guard
GUARDED_DISPATCHES = {
    INFER_PY: ("_dispatch", "decode_n", "prefill_paged", "decode_iter",
               "prefill_suffix_paged", "spec_draft", "spec_verify"),
    STEP_PY: ("_dispatch",),
}

HOST_ENTROPY_PREFIXES = ("time.", "random.", "np.random.", "_np.random.",
                         "numpy.random.")


def check_cfg_hygiene(module: _ad.Module) -> List[Tuple]:
    out = []
    for cls in module.classes.values():
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)) or \
                    not fn.name.endswith("_cfg"):
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Name) and \
                        node.func.id == "float":
                    out.append((
                        node.lineno,
                        f"{cls.name}.{fn.name}:float",
                        f"{cls.name}.{fn.name} coerces a config element "
                        "with float(...) — a float in a program-cache "
                        "key compiles a new program per VALUE; pass it "
                        "as a traced operand (jnp.float32) instead"))
                if isinstance(node, ast.Return):
                    for e in ast.walk(node):
                        if isinstance(e, ast.Constant) and \
                                isinstance(e.value, float):
                            out.append((
                                node.lineno,
                                f"{cls.name}.{fn.name}:float-literal",
                                f"{cls.name}.{fn.name} returns a float "
                                "literal in a config key — non-weak-type "
                                "literal churn"))
    return out


def check_traced_closures(module: _ad.Module, builders) -> List[Tuple]:
    out = []
    for cls in module.classes.values():
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)) or \
                    fn.name not in builders:
                continue
            closures = [n for n in ast.walk(fn)
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                        and n is not fn]
            for c in closures:
                for node in ast.walk(c):
                    if isinstance(node, (ast.If, ast.While)):
                        test = node.test
                        hazard = any(
                            (isinstance(s, ast.Attribute)
                             and s.attr == "shape")
                            or (isinstance(s, ast.Call)
                                and isinstance(s.func, ast.Name)
                                and s.func.id == "len")
                            for s in ast.walk(test))
                        if hazard:
                            out.append((
                                node.lineno,
                                f"{fn.name}.{c.name}:shape-branch",
                                f"traced closure {c.name} (in {fn.name}) "
                                "branches on .shape/len() — each shape "
                                "variant silently compiles another "
                                "program; bucket shapes at the input "
                                "layer instead"))
                    if isinstance(node, ast.Call):
                        name = _ad.dotted(node.func) or ""
                        if name.startswith(HOST_ENTROPY_PREFIXES):
                            out.append((
                                node.lineno,
                                f"{fn.name}.{c.name}:host-entropy",
                                f"traced closure {c.name} calls {name} — "
                                "the value is frozen at trace time, not "
                                "per step"))
    return out


def check_guard_accounting(module: _ad.Module, dispatches) -> List[Tuple]:
    out = []
    for cls in module.classes.values():
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)) or \
                    fn.name not in dispatches:
                continue
            fetches = False
            observes = False
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = _ad.dotted(node.func) or ""
                if name.startswith("self._get_") and name.endswith("_fn") \
                        or name in ("self._fwd_fn", "self._step_fn"):
                    fetches = True
                if ".observe" in name and "guard" in name.lower() or \
                        name.endswith("compile_guard.observe"):
                    observes = True
            if fetches and not observes:
                out.append((
                    fn.lineno, f"{cls.name}.{fn.name}:unaccounted",
                    f"{cls.name}.{fn.name} dispatches a jitted program "
                    "without compile_guard.observe(...) — its signatures "
                    "are invisible to the recompile alarm"))
    return out


def run_guard_crosscheck(programs) -> List[str]:
    """Executable cross-check on the real engine: same shapes + changed
    Python scalar knobs must not grow the signature set or the program
    cache."""
    import numpy as np

    msgs = []
    eng = programs.infer_engine
    src = np.zeros((2, 8), np.int32)
    vl = np.full((2,), 8, np.int32)
    eng.decode_n(src, vl, max_new_tokens=4)
    sigs = eng.compile_guard.signatures
    progs = len(eng._decode_fns)
    eng.decode_n(src, vl, max_new_tokens=4, temperature=0.7)
    eng.decode_n(src, vl, max_new_tokens=4, temperature=0.31)
    if eng.compile_guard.signatures != sigs:
        msgs.append(
            "InferStep.decode_n: changing temperature at fixed shapes "
            f"grew the signature set ({sigs} -> "
            f"{eng.compile_guard.signatures}) — a Python scalar is "
            "leaking into the traced signature")
    if len(eng._decode_fns) != progs:
        msgs.append(
            "InferStep.decode_n: changing temperature minted "
            f"{len(eng._decode_fns) - progs} new jitted program(s) — "
            "temperature must stay out of the program-cache key")
    # repeating the identical call must be signature-stable too
    again = eng.compile_guard.signatures
    eng.decode_n(src, vl, max_new_tokens=4)
    if eng.compile_guard.signatures != again:
        msgs.append(
            "InferStep.decode_n: re-dispatching the identical prompt "
            "signature grew the RecompileGuard signature set — "
            "signature accounting is unstable")
    return msgs


@register
class RecompileHazardPass(AnalysisPass):
    name = "recompile-hazard"
    ir = "jaxpr"
    description = ("config keys stay int/str, traced closures free of "
                   "shape branches/host entropy, dispatches guard-"
                   "accounted, runtime guard cross-check")

    def run(self, ctx):
        findings = []
        for path in (STEP_PY, INFER_PY):
            mod = ctx.ast.module(path)
            for ln, key, msg in check_cfg_hygiene(mod):
                findings.append(self.finding("cfg-hygiene", path, ln,
                                             key=key, message=msg))
            for ln, key, msg in check_traced_closures(
                    mod, TRACED_BUILDERS[path]):
                findings.append(self.finding("traced-shape-branch", path,
                                             ln, key=key, message=msg))
            for ln, key, msg in check_guard_accounting(
                    mod, GUARDED_DISPATCHES[path]):
                findings.append(self.finding("guard-accounting", path,
                                             ln, key=key, message=msg))
        for msg in run_guard_crosscheck(ctx.programs):
            findings.append(self.finding(
                "guard-crosscheck", INFER_PY, 0, key=msg[:80],
                message=msg))
        return findings
