"""resource-leak pass: a linear must-release dataflow over the serving
plane's exception edges, composed interprocedurally (callgraph.py).

Resources tracked (the PR 11/12 bug shapes):

- **pool pages** — ``PagePool.alloc``/``adopt_ref``/``ensure`` acquire
  pages into a slot; ``release``/``reset`` give them back. An exception
  that escapes between acquire and release — across any number of
  calls — leaves the pages owned by a dead admission (the PR 12 re-key
  refcount bug shape).
- **prefix-trie refcounts** — ``cache_acquire`` pins a page for the
  radix trie; ``cache_release``/``flush`` unpin.
- **the disagg baton** — a ``queue.Queue(maxsize=1)`` ownership token:
  a ``get`` that an exception can bypass before the matching ``put``
  deadlocks every later prefill (the PR 11 baton protocol).
- **futures** — a ``GenerationResult`` bound from ``.submit(...)`` or
  constructed directly must be failed on every error path after it
  exists; an escaping raise that no handler converts into
  ``fut._fail(...)`` strands the caller until its deadline
  (rule ``future-path``).
- **HandoffStash entries** — structural: a ``*Stash`` buffer with
  ``put``/``pop`` must consult a clock (TTL) somewhere, or entries whose
  ``kv_push`` landed but whose ``submit`` never arrives survive until
  capacity eviction (rule ``stash-expiry``).

Rules ``leak-on-raise`` (pool/cache/baton): an acquire followed — before
the matching same-receiver release — by a statement where an exception
escapes the function creates an *obligation* unless an enclosing
``finally``/handler releases the receiver. Obligations propagate up the
resolved call graph; a call site consumed by a broad non-re-raising
handler (e.g. ``except Exception: self._poison(e)``) discharges them.
Obligations still held at a root — a thread entry or a function with no
in-graph callers — are findings, fingerprinted at the ACQUIRE site.

Limitations (deliberate): linear statement order per function (no path
sensitivity); a named handler that releases discharges even though it
may not catch every class; broad handlers without an explicit release
discharge too (the error path was designed — ``_adopt``'s re-prefill
fallback keeps its slot pages on purpose). Violations the model cannot
prove safe belong in the baseline with a reason.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import AnalysisPass, register
from .. import ast_driver as _ad
from .. import callgraph as _cg

MODULES = (
    "mxnet_tpu/serving/batcher.py",
    "mxnet_tpu/serving/pages.py",
    "mxnet_tpu/serving/prefix.py",
    "mxnet_tpu/serving/router.py",
    "mxnet_tpu/serving/watcher.py",
    "mxnet_tpu/serving/worker.py",
    "mxnet_tpu/serving/remote.py",
    "mxnet_tpu/serving/disagg.py",
    "mxnet_tpu/serving/transport.py",
    "mxnet_tpu/serving/faults.py",
    "tools/launch.py",
)

# kind -> (acquire attrs, release attrs)
KINDS = {
    "pool-page": (("alloc", "adopt_ref", "ensure"),
                  ("release", "reset")),
    "cache-ref": (("cache_acquire",),
                  ("cache_release", "flush", "reset")),
    "baton": (("get", "get_nowait"), ("put", "put_nowait")),
}

FUTURE_CTORS = {"GenerationResult"}
CLOCK_MARKS = ("monotonic", "perf_counter", "time.time")


class Obligation:
    """One unreleased acquire that an exception edge can bypass."""

    __slots__ = ("kind", "recv", "origin", "acquire_line", "escape_line",
                 "why")

    def __init__(self, kind, recv, origin, acquire_line, escape_line,
                 why):
        self.kind = kind
        self.recv = recv
        self.origin = origin          # NodeKey of the acquiring function
        self.acquire_line = acquire_line
        self.escape_line = escape_line
        self.why = why

    def ident(self):
        return (self.kind, self.recv, self.origin, self.acquire_line)


def _release_calls(node, kind, recv):
    rel = KINDS[kind][1]
    return [n for n in node.info.calls()
            if isinstance(n.func, ast.Attribute) and n.func.attr in rel
            and _cg.receiver_name(n.func.value) == recv]


def _acquire_sites(graph, node):
    """(kind, recv, call) acquire sites in one function, excluding the
    resource-defining class's own internals (receiver ``self``)."""
    owner = node.owner if node.owner in graph.classes else None
    out = []
    for n in node.info.calls():
        f = n.func
        if not isinstance(f, ast.Attribute):
            continue
        if isinstance(f.value, ast.Name) and f.value.id == "self":
            continue  # PagePool/PrefixCache internals manage themselves
        recv = _cg.receiver_name(f.value)
        if recv is None:
            continue
        if f.attr in KINDS["pool-page"][0]:
            t = graph.types.expr_class(owner, f.value)
            if t == "PagePool" or recv.split(".")[-1].endswith("pool"):
                out.append(("pool-page", recv, n))
        elif f.attr in KINDS["cache-ref"][0]:
            out.append(("cache-ref", recv, n))
        elif f.attr in KINDS["baton"][0] and owner is not None \
                and "." not in recv:
            ctor = graph.types.attr_ctor.get((owner, recv))
            if ctor is None or _ad.dotted(ctor.func) != "queue.Queue":
                continue
            size = _cg.kwarg(ctor, "maxsize")
            if size is None and ctor.args:
                size = ctor.args[0]
            if isinstance(size, ast.Constant) and size.value == 1:
                out.append(("baton", recv, n))
    return out


def _protected(node, src, kind, recv):
    """True when some enclosing try's ``finally`` (or a handler) releases
    the receiver — the raise still escapes, but the resource does not."""
    rel = KINDS[kind][1]
    for t in node.info.tries_of(src):
        bodies = [t.finalbody] + [h.body for h in t.handlers]
        for body in bodies:
            for stmt in _ad.walk_statements(body):
                for n in ast.walk(stmt):
                    if isinstance(n, ast.Call) and \
                            isinstance(n.func, ast.Attribute) and \
                            n.func.attr in rel and \
                            _cg.receiver_name(n.func.value) == recv:
                        return True
    return False


def _function_obligations(graph, node):
    out = []
    acquires = _acquire_sites(graph, node)
    if not acquires:
        return out
    points = graph.escaping_points(node.key)
    for kind, recv, call in acquires:
        rels = sorted(n.lineno for n in _release_calls(node, kind, recv)
                      if n.lineno > call.lineno)
        window_end = rels[0] if rels else float("inf")
        for ln, desc, src in points:
            if src is call or ln <= call.lineno or ln > window_end:
                continue
            if _protected(node, src, kind, recv):
                continue
            out.append(Obligation(kind, recv, node.key, call.lineno,
                                  ln, desc))
            break
    return out


def _propagate(graph, seeds):
    """Push obligations up the caller graph; a call site whose enclosing
    handler consumes the exception discharges them. Returns
    {NodeKey: {Obligation ident: Obligation}}."""
    held: Dict[_cg.NodeKey, Dict[tuple, Obligation]] = {}
    for key, obs in seeds.items():
        held.setdefault(key, {})
        for ob in obs:
            held[key][ob.ident()] = ob
    changed = True
    while changed:
        changed = False
        for key in list(held):
            for caller_key, call in graph.callers_of(key):
                caller = graph.nodes[caller_key]
                if caller.info.caught(call):
                    continue  # handled edge: obligation discharged
                bucket = held.setdefault(caller_key, {})
                for ident, ob in held[key].items():
                    if ident not in bucket:
                        bucket[ident] = ob
                        changed = True
    return held


def _leak_findings(graph, held):
    """Obligations still held at a root (thread entry / no callers)."""
    out = {}
    for key, obs in held.items():
        is_root = key in graph.thread_entries \
            or not graph.callers_of(key)
        if not is_root:
            continue
        for ob in obs.values():
            origin = graph.nodes[ob.origin]
            entry = out.setdefault(ob.ident(), (ob, origin, []))
            entry[2].append(f"{key[0]}.{key[1]}")
    findings = []
    for ob, origin, roots in out.values():
        findings.append((
            origin.module.path, ob.acquire_line,
            f"{origin.owner}.{origin.name}", ob.kind, ob.recv,
            f"{ob.kind} acquired via {ob.recv!r} at "
            f"{origin.owner}.{origin.name}:{ob.acquire_line} can leak: "
            f"an exception escaping at line {ob.escape_line} "
            f"({ob.why}) reaches {', '.join(sorted(set(roots)))} with "
            f"no release on the unwind path"))
    return findings


def _fails_future(node, src, futname):
    """True when some enclosing try has a handler that resolves the
    future (``fut._fail``/``set_exception``) — re-raising after is fine,
    the caller-visible contract is kept."""
    for t in node.info.tries_of(src):
        for h in t.handlers:
            for stmt in _ad.walk_statements(h.body):
                for n in ast.walk(stmt):
                    if isinstance(n, ast.Call) and \
                            isinstance(n.func, ast.Attribute) and \
                            n.func.attr in ("_fail", "set_exception") and \
                            isinstance(n.func.value, ast.Name) and \
                            n.func.value.id == futname:
                        return True
    return False


def _benign_raises(fn, futname):
    """Raise statements inside an except-handler that already resolved
    the future (``fut._fail(e); raise``): the caller-visible contract is
    kept — propagating the error upward on top of it is fine."""
    out = set()
    for h in (n for n in ast.walk(fn)
              if isinstance(n, ast.ExceptHandler)):
        fails = any(
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr in ("_fail", "set_exception")
            and isinstance(n.func.value, ast.Name)
            and n.func.value.id == futname
            for n in ast.walk(h))
        if fails:
            out.update(id(n) for n in ast.walk(h)
                       if isinstance(n, ast.Raise))
    return out


def _future_findings(graph):
    out = []
    for key, node in graph.nodes.items():
        binds = []  # (name, line)
        for n in node.info.nodes:
            if isinstance(n, ast.Assign) and \
                    isinstance(n.value, ast.Call) and \
                    len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name):
                f = n.value.func
                d = _ad.dotted(f) or ""
                is_submit = isinstance(f, ast.Attribute) \
                    and f.attr == "submit" \
                    and (graph.types.expr_class(
                        key[0] if key[0] in graph.classes else None,
                        f.value) == "RpcClient"
                        or (_cg.receiver_name(f.value) or "")
                        .split(".")[-1].endswith("client")
                        or (_cg.receiver_name(f.value) or "")
                        .split(".")[-1].endswith("batcher"))
                is_ctor = d.rsplit(".", 1)[-1] in FUTURE_CTORS
                if is_submit or is_ctor:
                    binds.append((n.targets[0].id, n.lineno))
        if key in graph.thread_entries:
            for a in node.fn.args.args:
                if a.arg in ("fut", "future"):
                    binds.append((a.arg, node.fn.lineno))
        if not binds:
            continue
        points = graph.escaping_points(key)
        for futname, bline in binds:
            benign = _benign_raises(node.fn, futname)
            for ln, desc, src in points:
                if ln <= bline:
                    continue
                if id(src) in benign or node.info.caught(src) or \
                        _fails_future(node, src, futname):
                    continue
                out.append((
                    node.module.path, ln,
                    f"{node.owner}.{node.name}", futname,
                    f"{node.owner}.{node.name} holds future "
                    f"{futname!r} (bound at line {bline}) but an "
                    f"exception escaping at line {ln} ({desc}) never "
                    f"fails it — the caller waits until its deadline"))
                break
    return out


def _stash_findings(graph, rel_set):
    out = []
    for cname, model in sorted(graph.classes.items()):
        if not cname.endswith("Stash") or \
                model.module.path not in rel_set:
            continue
        put = model.method("put")
        pop = model.method("pop")
        if put is None or pop is None:
            continue
        clocked = False
        for fn in (put, pop, model.method("__init__")):
            if fn is None:
                continue
            for n in ast.walk(fn):
                d = _ad.dotted(n) if isinstance(n, (ast.Attribute,
                                                    ast.Name)) else None
                if d and any(m in d for m in CLOCK_MARKS):
                    clocked = True
        if not clocked:
            out.append((
                model.module.path, model.node.lineno, cname,
                f"{cname}.put/pop never consult a clock: an entry whose "
                f"consumer died survives until capacity eviction — add "
                f"a TTL purge (expired entries are re-prefilled "
                f"anyway)"))
    return out


def analyze(index: _ad.AstIndex, rel_paths=MODULES):
    """Returns (leaks, futures, stashes); see the tuple layouts in the
    ``_*_findings`` helpers. The seeded-control entry point."""
    graph = _cg.ProjectGraph(index, rel_paths)
    seeds = {}
    for key, node in graph.nodes.items():
        obs = _function_obligations(graph, node)
        if obs:
            seeds[key] = obs
    held = _propagate(graph, seeds)
    return (_leak_findings(graph, held), _future_findings(graph),
            _stash_findings(graph, set(graph.rel_paths)))


@register
class ResourceLeakPass(AnalysisPass):
    name = "resource-leak"
    ir = "ast"
    description = ("pool pages / trie refcounts / disagg baton / futures "
                   "released on every path incl. exception edges; stash "
                   "entries expire")

    def run(self, ctx):
        findings = []
        leaks, futures, stashes = analyze(ctx.ast)
        for path, line, where, kind, recv, msg in leaks:
            findings.append(self.finding(
                "leak-on-raise", path, line,
                key=f"{where}:{kind}:{recv}", message=msg))
        for path, line, where, futname, msg in futures:
            findings.append(self.finding(
                "future-path", path, line, key=f"{where}:{futname}",
                message=msg))
        for path, line, cname, msg in stashes:
            findings.append(self.finding(
                "stash-expiry", path, line, key=f"{cname}:no-expiry",
                message=msg))
        return findings
