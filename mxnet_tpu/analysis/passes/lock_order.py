"""lock-order pass: a compositional race/deadlock detector for the
threaded serving plane (in the spirit of RacerD: per-method summaries,
no whole-program interleaving exploration).

Scope: ``serving/`` + ``telemetry/watchdog.py`` — the code that runs a
dispatcher/monitor thread against caller-side ``submit``/``stop`` APIs.
Three rules:

- **deadlock-cycle** — build the lock-acquisition graph (lock held ->
  lock acquired, interprocedural through ``self.m()`` calls) across the
  module set; any cycle is a potential deadlock, including a plain
  ``Lock`` re-acquired while held (self-deadlock).
- **blocking-under-lock** — ``future.result()``, ``thread.join()``,
  ``queue.get()``, ``Event.wait()``, ``time.sleep()``, device syncs
  (``block_until_ready``/``asnumpy``) or an engine dispatch
  (``decode_n``/``decode_iter``/``prefill_paged``/``warmup``) while
  holding a lock stalls every thread contending for it — the exact shape
  of the hung-replica incidents the router's health scoring exists to
  catch. ``cond.wait()`` on the *held* condition is legal (it releases).
- **shared-state** — an attribute written without a lock in one thread
  domain (worker = reachable from a ``threading.Thread(target=...)``
  entry; caller = reachable from the public API) while the other domain
  also writes or *iterates* it. Plain scalar loads are ignored
  (CPython-atomic); iterating reads (``for``/``sorted``/``list``/...)
  are flagged because a concurrent ``append`` corrupts them. Thread-safe
  containers (``queue.Queue``, ``threading.Event``...) are exempt, as is
  ``__init__``-only setup.

Limitations (documented, deliberate): receiver types are not chased
across objects — ``rep.batcher.submit(...)`` is matched by attribute
name only, and per-class analysis does not see writes to *other*
objects' attributes. Precise enough for this package's code shapes;
violations the model cannot prove safe (e.g. writes that only happen
after ``Thread.join``) live in the committed baseline with a reason.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import AnalysisPass, register
from .. import ast_driver as _ad

MODULES = (
    "mxnet_tpu/serving/batcher.py",
    "mxnet_tpu/serving/router.py",
    "mxnet_tpu/serving/watcher.py",
    "mxnet_tpu/serving/faults.py",
    "mxnet_tpu/serving/pages.py",
    "mxnet_tpu/serving/prefix.py",
    "mxnet_tpu/serving/transport.py",
    "mxnet_tpu/serving/worker.py",
    "mxnet_tpu/serving/remote.py",
    "mxnet_tpu/serving/disagg.py",
    "mxnet_tpu/serving/tracing.py",
    "mxnet_tpu/telemetry/watchdog.py",
    "tools/launch.py",
)

LOCK_TYPES = {"threading.Lock", "threading.RLock", "threading.Condition"}
REENTRANT_TYPES = {"threading.RLock"}
# objects with internal synchronization: mutating them without an outer
# lock is safe, so they are exempt from the shared-state rule
THREADSAFE_TYPES = {
    "threading.Event", "threading.Thread", "threading.Semaphore",
    "threading.BoundedSemaphore", "threading.Barrier",
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue",
} | LOCK_TYPES

# attribute-name matched blocking calls (receiver-agnostic)
BLOCKING_ATTRS = {"result", "join", "block_until_ready", "asnumpy",
                  "item", "tolist", "acquire"}
# engine dispatches: firing (or compiling) a device program while
# holding a host lock couples every contending thread to device latency
DISPATCH_ATTRS = {"decode_n", "decode_iter", "prefill_paged", "warmup",
                  "spec_draft", "spec_verify"}
QUALIFIED_BLOCKING = {"time.sleep", "jax.block_until_ready"}

PUBLIC_DUNDERS = {"__call__", "__enter__", "__exit__", "__iter__",
                  "__next__"}

LockId = Tuple[str, str]  # (class name, attr name)


class ClassConcurrency:
    """Per-class summaries: locks, thread domains, per-method lock and
    blocking facts — the compositional unit of the analysis."""

    def __init__(self, model: _ad.ClassModel):
        self.model = model
        self.name = model.name
        self.locks: Dict[str, str] = {}       # attr -> lock ctor
        self.threadsafe: Set[str] = set()     # attrs with internal sync
        self.worker_entries: Set[str] = set()
        self.edges: List[Tuple[LockId, LockId, str, int]] = []
        # (method, lineno, message, held) for blocking calls under a lock
        self.blocking: List[Tuple[str, int, str, Tuple[str, ...]]] = []
        self.acquires: Dict[str, Set[str]] = {}   # method -> lock attrs
        # method -> [(lineno, message)] blocking calls ANYWHERE in it
        self.blocks_in: Dict[str, List[Tuple[int, str]]] = {}
        self.calls: Dict[str, Set[str]] = {}      # self-call graph
        self.locked_lines: Dict[str, Set[int]] = {}
        self.held_at: Dict[str, Dict[int, Tuple[str, ...]]] = {}
        self._scan_attrs()
        for mname, (fn, mod) in self.model.methods.items():
            self.calls[mname] = set()
            self.acquires[mname] = set()
            self.blocks_in[mname] = []
            self.locked_lines[mname] = set()
            self.held_at[mname] = {}
            # repo convention: a ``*_locked`` method runs with the
            # class's lock already held by its caller
            held0: Tuple[str, ...] = ()
            if mname.endswith("_locked") and self.locks:
                held0 = (sorted(self.locks)[0],)
            for stmt in fn.body:
                self._visit(mname, mod, stmt, held0)
        self._find_thread_entries()
        self.worker_set = self._closure(self.worker_entries)
        public = {n for n in self.model.methods
                  if (not n.startswith("_")) or n in PUBLIC_DUNDERS}
        self.caller_set = self._closure(public)
        self.setup_set = ({"__init__"} | self._closure({"__init__"})) \
            - self.worker_set - self.caller_set | {"__init__"}

    # ------------------------------------------------------------ scanning
    def _scan_attrs(self):
        for mname, (fn, _mod) in self.model.methods.items():
            for node in ast.walk(fn):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                if not isinstance(node.value, ast.Call):
                    continue
                ctor = _ad.dotted(node.value.func)
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    attr = _ad.self_attr(t)
                    if attr is None:
                        continue
                    if ctor in LOCK_TYPES:
                        self.locks[attr] = ctor
                    if ctor in THREADSAFE_TYPES:
                        self.threadsafe.add(attr)

    def _lock_of(self, expr) -> Optional[str]:
        attr = _ad.self_attr(expr)
        return attr if attr is not None and attr in self.locks else None

    def _visit(self, mname, mod, node, held: Tuple[str, ...]):
        """One recursive walk per method tracking the held-lock tuple."""
        ln = getattr(node, "lineno", None)
        if ln is not None:
            if held:
                self.locked_lines[mname].add(ln)
            prev = self.held_at[mname].get(ln, ())
            if len(held) >= len(prev):
                self.held_at[mname][ln] = held
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new: List[str] = []
            for item in node.items:
                self._visit(mname, mod, item.context_expr,
                            held + tuple(new))
                lk = self._lock_of(item.context_expr)
                if lk is not None:
                    for h in held + tuple(new):
                        self.edges.append((
                            (self.name, h), (self.name, lk),
                            f"{mod.path}:{self.name}.{mname}",
                            node.lineno))
                    if lk in held and \
                            self.locks[lk] not in REENTRANT_TYPES:
                        self.edges.append((
                            (self.name, lk), (self.name, lk),
                            f"{mod.path}:{self.name}.{mname}",
                            node.lineno))
                    new.append(lk)
                    self.acquires[mname].add(lk)
            for stmt in node.body:
                self._visit(mname, mod, stmt, held + tuple(new))
            return
        if isinstance(node, ast.Call):
            self._check_call(mname, node, held)
        for child in ast.iter_child_nodes(node):
            self._visit(mname, mod, child, held)

    def _check_call(self, mname, call, held):
        attr = _ad.self_attr(call.func)
        if attr is not None and attr in self.model.methods:
            self.calls[mname].add(attr)
        msg = self._blocking_reason(call, held)
        if msg is not None:
            self.blocks_in[mname].append((call.lineno, msg))
            if held:
                self.blocking.append((mname, call.lineno, msg,
                                      tuple(held)))

    def _blocking_reason(self, call, held) -> Optional[str]:
        f = call.func
        if not isinstance(f, ast.Attribute):
            return None
        name = _ad.dotted(f)
        if name in QUALIFIED_BLOCKING:
            return f"{name}(...) stalls the thread"
        if f.attr == "wait":
            recv = _ad.self_attr(f.value)
            if recv is not None and recv in held and \
                    self.locks.get(recv) == "threading.Condition":
                return None  # cond.wait on the held condition releases it
            return f"{name or '.' + f.attr}(...) blocks until signaled"
        if f.attr == "get":
            recv = _ad.dotted(f.value) or ""
            if "queue" in recv.lower():
                kwargs = {k.arg for k in call.keywords}
                if "timeout" in kwargs or (not call.args and not kwargs):
                    return f"{recv}.get(...) blocks on the queue"
            return None
        if f.attr in BLOCKING_ATTRS:
            return f".{f.attr}() blocks (device sync / thread wait)"
        if f.attr in DISPATCH_ATTRS:
            return (f".{f.attr}(...) fires a device dispatch — device "
                    "latency while holding a host lock")
        return None

    def _find_thread_entries(self):
        for mname, (fn, _mod) in self.model.methods.items():
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and \
                        _ad.dotted(node.func) == "threading.Thread":
                    for kw in node.keywords:
                        if kw.arg == "target":
                            t = _ad.self_attr(kw.value)
                            if t is not None:
                                self.worker_entries.add(t)

    # ------------------------------------------------------------ summaries
    def _closure(self, roots: Set[str]) -> Set[str]:
        seen: Set[str] = set()
        stack = [r for r in roots if r in self.model.methods]
        while stack:
            m = stack.pop()
            if m in seen:
                continue
            seen.add(m)
            stack.extend(c for c in self.calls.get(m, ())
                         if c in self.model.methods and c not in seen)
        return seen

    def transitive_acquires(self, mname: str) -> Set[str]:
        out: Set[str] = set()
        for m in self._closure({mname}):
            out |= self.acquires.get(m, set())
        return out

    def transitive_blocking(self, mname: str) -> List[Tuple[int, str]]:
        out = []
        for m in self._closure({mname}):
            out.extend(self.blocks_in.get(m, ()))
        return out

    def domains_of(self, mname: str) -> Set[str]:
        out = set()
        if mname in self.worker_set:
            out.add("worker")
        if mname in self.caller_set:
            out.add("caller")
        return out


def _interprocedural(cc: ClassConcurrency):
    """Held-lock -> callee-acquired-lock edges and blocking-via-self-call
    findings, using the per-method summaries."""
    for mname, (fn, mod) in cc.model.methods.items():
        if not cc.locked_lines.get(mname):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = _ad.self_attr(node.func)
            if callee is None or callee not in cc.model.methods:
                continue
            held = cc.held_at[mname].get(node.lineno, ())
            if not held:
                continue
            for lk in cc.transitive_acquires(callee):
                for h in held:
                    cc.edges.append((
                        (cc.name, h), (cc.name, lk),
                        f"{mod.path}:{cc.name}.{mname} -> "
                        f"self.{callee}()", node.lineno))
            for ln, msg in cc.transitive_blocking(callee):
                cc.blocking.append((
                    mname, node.lineno,
                    f"self.{callee}() {msg} (line {ln})", tuple(held)))


def _find_cycles(edges):
    """Cycles in the lock graph: self-loops (non-reentrant re-acquire)
    plus multi-lock SCCs (Tarjan)."""
    adj: Dict[LockId, Set[LockId]] = {}
    where: Dict[Tuple[LockId, LockId], Tuple[str, int]] = {}
    for a, b, site, ln in edges:
        if a == b:
            # recorded only for deliberate non-reentrant re-acquisition
            adj.setdefault(a, set()).add(b)
        else:
            adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set())
        where.setdefault((a, b), (site, ln))
    cycles = []
    for a in adj:
        if a in adj[a]:
            cycles.append(([a, a], [where[(a, a)]]))
    index: Dict[LockId, int] = {}
    low: Dict[LockId, int] = {}
    on: Set[LockId] = set()
    stack: List[LockId] = []
    counter = [0]
    sccs = []

    def strongconnect(v):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in adj.get(v, ()):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1:
                sccs.append(comp)

    for v in list(adj):
        if v not in index:
            strongconnect(v)
    for comp in sccs:
        sites = [where[(a, b)] for a in comp for b in adj.get(a, ())
                 if b in comp and (a, b) in where]
        cycles.append((comp, sites))
    return cycles


def _shared_state(cc: ClassConcurrency):
    """The shared-state rule over one class."""
    if not cc.worker_entries:
        return []  # no background thread: nothing to race against
    out = []
    accesses: Dict[str, list] = {}
    for mname, (fn, mod) in cc.model.methods.items():
        domains = cc.domains_of(mname)
        if not domains or mname in cc.setup_set:
            continue
        fm = _ad.FunctionModel(fn, mod)
        locked = cc.locked_lines.get(mname, set())
        for attr, ln, kind in fm.self_stores():
            if attr in cc.locks or attr in cc.threadsafe or \
                    attr.startswith("__"):
                continue
            accesses.setdefault(attr, []).append(
                (mname, ln, "write", ln in locked, domains))
        for attr, ln, iterated in fm.self_loads():
            if not iterated or attr in cc.locks or \
                    attr in cc.threadsafe or attr.startswith("__"):
                continue
            accesses.setdefault(attr, []).append(
                (mname, ln, "iter-read", ln in locked, domains))
    for attr, acc in sorted(accesses.items()):
        domains = set().union(*(a[4] for a in acc))
        writes = [a for a in acc if a[2] == "write"]
        unlocked = [a for a in acc if not a[3]]
        if domains >= {"worker", "caller"} and writes and unlocked:
            sites = ", ".join(
                f"{m}:{ln} ({kind}{'' if lk else ' unlocked'} "
                f"{'/'.join(sorted(doms))})"
                for m, ln, kind, lk, doms in acc[:6])
            out.append((cc.model.module.path, unlocked[0][1], cc.name,
                        attr,
                        f"{cc.name}.{attr} is accessed from both the "
                        f"dispatcher thread and callers with at least "
                        f"one unsynchronized access: {sites}"))
    return out


def analyze(index: _ad.AstIndex, rel_paths=MODULES):
    """Run the full analysis; returns (cycles, blocking, shared) where
    blocking = [(path, line, class, method, msg, held)] and shared =
    [(path, line, class, attr, msg)]."""
    models = index.classes_in(list(rel_paths))
    wanted = set(rel_paths)
    all_edges = []
    blocking = []
    shared = []
    for cname, model in sorted(models.items()):
        if model.module.path not in wanted:
            continue
        cc = ClassConcurrency(model)
        _interprocedural(cc)
        all_edges.extend(cc.edges)
        for mname, ln, msg, held in cc.blocking:
            blocking.append((cc.model.methods[mname][1].path, ln, cname,
                             mname, msg, "+".join(sorted(set(held)))))
        shared.extend(_shared_state(cc))
    return _find_cycles(all_edges), blocking, shared


@register
class LockOrderPass(AnalysisPass):
    name = "lock-order"
    ir = "ast"
    description = ("serving-plane deadlock cycles, blocking calls under "
                   "locks, unsynchronized cross-thread state")

    def run(self, ctx):
        findings = []
        cycles, blocking, shared = analyze(ctx.ast)
        for comp, sites in cycles:
            locks = " -> ".join(f"{c}.{a}" for c, a in comp)
            site, ln = sites[0] if sites else (MODULES[0], 0)
            findings.append(self.finding(
                "deadlock-cycle", site.split(":")[0], ln, key=locks,
                message=f"lock acquisition cycle {locks} — threads "
                "taking these locks in different orders can deadlock "
                f"(first edge at {site}:{ln})"))
        for path, ln, cname, mname, msg, held in blocking:
            findings.append(self.finding(
                "blocking-under-lock", path, ln,
                key=f"{cname}.{mname}:{msg[:50]}",
                message=f"{cname}.{mname} holds [{held}] while: {msg}"))
        for path, ln, cname, attr, msg in shared:
            findings.append(self.finding(
                "shared-state", path, ln, key=f"{cname}.{attr}",
                message=msg))
        return findings
