"""sharding-placement pass: declared shardings must actually hold.

Port of ``tools/check_sharding.py`` (PR 6) onto the pass framework —
same three checks, same assertions. GSPMD fails soft: an array placed
with the wrong (or no) sharding still computes — XLA inserts resharding
copies and the "FSDP" run silently trains fully replicated, OOMing at
exactly the scale sharding was meant to unlock.

1. **Declared == placed** for every param/opt-state leaf entering the
   jitted TrainStep/InferStep (live ``Array.sharding`` comparison).
2. **Placements survive the step** — after one real (donated) dispatch
   the updated state still carries the declared shardings.
3. **No silent replication fallback** — every pattern rule matches,
   fsdp shards everything shardable, something is partitioned at all.
"""

from __future__ import annotations

import os

from ..core import AnalysisPass, register

SHARDING_PY = "mxnet_tpu/parallel/sharding.py"


# ----------------------------------------------------------------- checks
def declared_shardings(step) -> dict:
    """name -> declared NamedSharding for every param of a built step."""
    if step._param_sharding is None:
        return {}
    out = {}
    for name, v in step._values.items():
        if hasattr(step._param_sharding, "__call__"):
            try:
                out[name] = step._param_sharding(name)
            except TypeError:
                # InferStep's placement closure takes (name, shape)
                out[name] = step._param_sharding(name, v.shape)
    return out


def _matches(got, want, ndim) -> bool:
    """Sharding equivalence (``is_equivalent_to`` ignores PartitionSpec
    canonicalization like trailing-None stripping)."""
    if got is None:
        return False
    try:
        return bool(got.is_equivalent_to(want, ndim))
    except Exception:  # noqa: BLE001 - cross-type comparisons
        return got == want


def check_step_placement(step, label="TrainStep") -> list:
    """Check (1): live param/opt-state arrays carry the declared
    shardings."""
    violations = []
    want = declared_shardings(step)
    if not want:
        return [f"{label}: no param shardings declared (mesh missing?)"]
    for name, v in step._values.items():
        got = getattr(v, "sharding", None)
        if not _matches(got, want[name], v.ndim):
            violations.append(
                f"{label}: param {name} placed with {got}, declared "
                f"{want[name].spec}")
    for name, st in getattr(step, "_opt_state", {}).items():
        for i, s in enumerate(st):
            got = getattr(s, "sharding", None)
            if not _matches(got, want[name], s.ndim):
                violations.append(
                    f"{label}: opt state {name}[{i}] placed with {got}, "
                    f"declared {want[name].spec} (moments must follow "
                    "their param — the ZeRO contract)")
    return violations


def check_post_step_placement(step, batch) -> list:
    """Check (2): run one real dispatch; the returned (donated) state
    must still carry the declared shardings."""
    step(*batch)
    violations = []
    want = declared_shardings(step)
    for name, v in step._train_vals.items():
        if not _matches(v.sharding, want[name], v.ndim):
            violations.append(
                f"TrainStep: param {name} came back from the jitted step "
                f"as {v.sharding.spec if hasattr(v.sharding, 'spec') else v.sharding}, "
                f"declared {want[name].spec} — out_shardings degraded")
    for name, st in step._opt_state.items():
        for i, s in enumerate(st):
            if not _matches(s.sharding, want[name], s.ndim):
                violations.append(
                    f"TrainStep: opt state {name}[{i}] degraded to "
                    f"{s.sharding} after one step")
    return violations


def check_rules_coverage(rules, shapes: dict, mesh) -> list:
    """Check (3): no rule silently falls back to full replication."""
    violations = []
    matched = {pat: 0 for pat, _ in rules.rules}
    partitioned = 0
    from jax.sharding import PartitionSpec

    for name, shape in shapes.items():
        spec, reason = rules.param_explain(name, shape, mesh)
        if reason.startswith("rule:"):
            matched[reason[5:]] += 1
        if reason == "replicated:indivisible":
            violations.append(
                f"rules: param {name} {shape} is large enough to shard "
                f"but NO dim divides the '{rules.fsdp_axis}' axis "
                f"(size {mesh.shape.get(rules.fsdp_axis)}) — silently "
                "fully replicated")
        if spec != PartitionSpec():
            partitioned += 1
    for pat, n in matched.items():
        if n == 0:
            violations.append(
                f"rules: pattern {pat!r} matched NO parameter — the "
                "placement it declares is silently inert")
    if rules.params == "fsdp" and partitioned == 0:
        violations.append(
            "rules: fsdp policy partitioned NOTHING (axis missing from "
            "the mesh, axis size 1, or every param under fsdp_min_size="
            f"{rules.fsdp_min_size}) — the run is fully replicated")
    return violations


# ------------------------------------------------------------ default rig
def ensure_devices():
    """Standalone runs on a bare CPU get a simulated 4-device platform
    (the tests' conftest already forces 8)."""
    if "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=4").strip()
        os.environ.setdefault("JAX_PLATFORMS", "cpu")


def build_default_setup():
    """A small FSDP-sharded TrainStep + InferStep on a 4-device mesh:
    the placement surface the lint walks."""
    import numpy as np

    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd, optimizer as opt
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import TrainStep, InferStep
    from mxnet_tpu.parallel import sharding as shard

    mesh = shard.make_global_mesh({"data": 4},
                                  devices=jax.devices()[:4])
    rules = shard.ShardingRules.fsdp(min_size=32)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(64, activation="relu"), nn.Dense(8))
    net.initialize()
    net(mx.nd.ones((8, 16)))
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                     opt.Adam(learning_rate=1e-3), mesh=mesh,
                     sharding=rules)
    eng = InferStep(net, mesh=mesh, sharding=rules)
    rng = np.random.RandomState(0)
    batch = (nd.array(rng.randn(8, 16).astype("float32")),
             nd.array(rng.randint(0, 8, 8)))
    shapes = {n: tuple(p._data.data.shape)
              for n, p in net.collect_params().items()}
    return mesh, rules, step, eng, batch, shapes


def run_checks(mesh, rules, step, eng, batch, shapes) -> list:
    violations = []
    violations += check_step_placement(step, "TrainStep")
    violations += check_rules_coverage(rules, shapes, mesh)
    violations += check_post_step_placement(step, batch)
    violations += check_step_placement(eng, "InferStep")
    return violations


@register
class ShardingPlacementPass(AnalysisPass):
    name = "sharding-placement"
    ir = "jaxpr"
    description = ("every param carries its declared NamedSharding; "
                   "placements survive the donated step; no silent "
                   "replication fallback")

    def run(self, ctx):
        import jax

        if len(jax.devices()) < 4:
            return [self.finding(
                "rig", SHARDING_PY, 0, key="devices",
                message="sharding-placement needs >= 4 devices (set "
                "XLA_FLAGS=--xla_force_host_platform_device_count=4 "
                "before jax import)")]
        setup = build_default_setup()
        return [self.finding("placement", SHARDING_PY, 0,
                             key=msg[:100], message=msg)
                for msg in run_checks(*setup)]
