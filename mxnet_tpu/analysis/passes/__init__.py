"""Pass modules: importing this package registers every pass.

Current roster (3 ported + 4 new + 2 consistency + 3 interprocedural):

========================  =====  ==========================================
pass                      IR     what it guards
========================  =====  ==========================================
``no-sync``               ast    jitted hot paths stay free of host syncs
``amp-purity``            jaxpr  no fp32 master feeds a low-precision dot;
                                 overflow-skip path sync-free
``sharding-placement``    jaxpr  declared NamedShardings actually hold
``lock-order``            ast    serving-plane deadlock cycles, blocking
                                 calls under locks, unsynchronized shared
                                 state across threads
``donation``              both   donate_argnums consumed + aliasable; big
                                 carried buffers donated; no host
                                 use-after-donate
``recompile-hazard``      both   traced-signature hygiene + RecompileGuard
                                 cross-check (scalar churn, shape branches)
``collective-placement``  both   no collectives in the decode path; host
                                 allreduce gated on mesh_spans_processes()
``env-vars``              meta   every MXTPU_*/MXNET_* read documented in
                                 docs/ENV_VARS.md (and vice versa)
``telemetry-names``       meta   every emitted metric family known to
                                 tools/telemetry_report.py
``resource-leak``         ast    pool pages / trie refcounts / disagg
                                 baton / futures released on every path
                                 incl. exception edges; stash entries
                                 expire (interprocedural)
``rpc-protocol``          ast    worker verb table vs every call site:
                                 handlers exist, reply keys cover reads,
                                 timeouts everywhere, fault reachability
``swap-barrier``          ast    stage-all dominates every flip over the
                                 same engine set; no registration window
                                 between stage and flip
========================  =====  ==========================================

The last three share the interprocedural layer in
``mxnet_tpu/analysis/callgraph.py`` (project call graph + per-function
exception summaries over ``AstIndex``).
"""

from . import no_sync  # noqa: F401
from . import amp_purity  # noqa: F401
from . import sharding_placement  # noqa: F401
from . import lock_order  # noqa: F401
from . import donation  # noqa: F401
from . import recompile  # noqa: F401
from . import collectives  # noqa: F401
from . import env_vars  # noqa: F401
from . import telemetry_names  # noqa: F401
from . import resource_leak  # noqa: F401
from . import rpc_protocol  # noqa: F401
from . import swap_barrier  # noqa: F401
