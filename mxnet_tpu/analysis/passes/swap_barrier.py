"""swap-barrier pass: stage-all must dominate every flip.

The two-phase weight swap (PR 4/8/11) has one invariant: a flip —
``eng.swap_params(staged=...)``, ``eng.swap_staged(version)``, or the
``swap`` verb — may only execute after the **stage** phase completed
over the **same engine set**, and if any stage fails, no engine flips
(else replicas diverge mid-fleet and batches mix weight versions).

Checked on the watcher/worker/router call graph:

- **flip-before-stage** — a coordinator function (one containing both
  stage and flip sites) whose first flip precedes its last stage in
  program order: the barrier is structurally inverted.
- **stage-fallthrough** — a stage site inside a ``try`` whose handler
  neither returns nor raises: a stage failure falls through into the
  flip phase and flips a partially-staged fleet.
- **stale-engine-set** — a flip loop iterating a sequence that is not
  provably the staged snapshot: the iterable must be assigned from an
  expression containing a stage call (or be a builtin re-iteration such
  as ``zip(local, staged)`` over such names) after function entry; a
  re-read of ``self._engines_fn()`` between stage and flip would admit
  a replica registered mid-swap without re-staging.
- **barrier-unlocked** — a coordinator that is neither ``*_locked``
  (caller-holds-lock convention, PR 9) nor holds a ``with self.<lock>``
  around both phases: registration can interleave between the phases.
- **unguarded-flip** — a non-coordinator method that flips without
  proof of prior staging: no ``staged is None -> raise/return`` guard
  on the value it flips with. Protocol forwarders (``swap_staged`` /
  ``swap_params`` themselves) and boot-time adoption
  (``swap_params(arrays=...)``) are exempt.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..core import AnalysisPass, register
from .. import ast_driver as _ad
from .. import callgraph as _cg

MODULES = (
    "mxnet_tpu/serving/watcher.py",
    "mxnet_tpu/serving/worker.py",
    "mxnet_tpu/serving/remote.py",
    "mxnet_tpu/serving/router.py",
    "tools/launch.py",
)

STAGE_ATTRS = frozenset({"stage_params", "stage_checkpoint"})
FLIP_ATTRS = frozenset({"swap_staged"})
FORWARDERS = frozenset({"swap_staged", "swap_params"})
LOCK_CTORS = frozenset({"Lock", "RLock", "Condition"})


def _is_stage(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute):
        if f.attr in STAGE_ATTRS:
            return True
        if f.attr == "call" and _cg.str_arg(call) == "stage":
            return True
    return False


def _is_flip(call: ast.Call) -> Optional[str]:
    """None, or why this call is a flip (for messages)."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    if f.attr in FLIP_ATTRS:
        return f.attr
    if f.attr == "call" and _cg.str_arg(call) == "swap":
        return 'call("swap")'
    if f.attr == "swap_params":
        # staged= is a flip of pre-staged values; arrays= is boot-time
        # adoption (stage+flip fused on a fresh process, exempt); bare
        # calls are forwarding shims handled by the forwarder exemption.
        if _cg.kwarg(call, "staged") is not None:
            return "swap_params(staged=...)"
        return None
    return None


def _stage_names(fn) -> Set[str]:
    """Local names bound (directly or via builtin re-iteration) to a
    stage result or to the engine snapshot a stage loop consumed."""
    out: Set[str] = set()
    # names assigned FROM an expression containing a stage call
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign):
            has_stage = any(_is_stage(c) for c in ast.walk(n.value)
                            if isinstance(c, ast.Call))
            if has_stage:
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    # names a stage for-loop iterated over: `for eng in local:
    #     eng.stage_params(...)` marks `local` as staged
    for n in ast.walk(fn):
        if isinstance(n, ast.For):
            has_stage = any(_is_stage(c) for c in ast.walk(n)
                            if isinstance(c, ast.Call))
            if has_stage and isinstance(n.iter, ast.Name):
                out.add(n.iter.id)
    # comprehension form: `staged = [e.stage_params(...) for e in local]`
    for n in ast.walk(fn):
        if isinstance(n, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
            has_stage = any(_is_stage(c) for c in ast.walk(n)
                            if isinstance(c, ast.Call))
            if has_stage:
                for gen in n.generators:
                    if isinstance(gen.iter, ast.Name):
                        out.add(gen.iter.id)
    return out


def _iter_names(expr) -> Optional[List[str]]:
    """The Name components of a flip loop's iterable; None if it calls
    anything that could refresh the engine set (non-builtin call)."""
    names: List[str] = []
    fn_names = set()
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            f = n.func
            ok = isinstance(f, ast.Name) and f.id in _cg.BUILTIN_ITER_FNS
            if not ok:
                return None
            fn_names.add(id(f))
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and id(n) not in fn_names:
            names.append(n.id)
    return names


def _holds_lock(fn, types, owner) -> bool:
    if fn.name.endswith("_locked"):
        return True
    for n in ast.walk(fn):
        if isinstance(n, ast.With):
            for item in n.items:
                attr = _ad.self_attr(item.context_expr)
                if attr is None:
                    continue
                d = types.ctor_dotted(owner, attr) if owner else None
                if d is not None and d.rsplit(".", 1)[-1] in LOCK_CTORS:
                    return True
    return False


def _guarded(fn, value_expr, flip_line) -> bool:
    """Is there an `if <value> is None: raise/return` (or truthiness
    equivalent) before the flip line, over the flipped value?"""
    d = _ad.dotted(value_expr)
    if d is None:
        return False
    for n in ast.walk(fn):
        if not isinstance(n, ast.If) or n.lineno > flip_line:
            continue
        test = n.test
        names = {_ad.dotted(c) for c in ast.walk(test)}
        if d not in names:
            continue
        for stmt in _ad.walk_statements(n.body):
            if isinstance(stmt, (ast.Raise, ast.Return)):
                return True
    return False


def analyze(index: _ad.AstIndex, rel_paths: Sequence[str] = MODULES):
    """Returns [(rule, path, line, key, message)] — the seeded-control
    entry point."""
    graph = _cg.ProjectGraph(index, rel_paths)
    out: List[Tuple[str, str, int, str, str]] = []

    for key, node in graph.nodes.items():
        fn = node.fn
        owner = key[0] if key[0] in graph.classes else None
        where = f"{key[0]}.{key[1]}"
        path = node.module.path
        stages = [c for c in node.info.calls() if _is_stage(c)]
        flips = [(c, why) for c in node.info.calls()
                 if (why := _is_flip(c))]
        if not flips:
            continue

        if stages:  # coordinator: owns the barrier
            first_flip = min(c.lineno for c, _w in flips)
            last_stage = max(c.lineno for c in stages)
            if first_flip < last_stage:
                out.append((
                    "flip-before-stage", path, first_flip, where,
                    f"{where} flips at line {first_flip} before the "
                    f"stage phase completes (last stage at line "
                    f"{last_stage}): barrier inverted"))
            for c in stages:
                for t in node.info.tries_of(c):
                    for h in t.handlers:
                        aborts = any(
                            isinstance(s, (ast.Return, ast.Raise))
                            for s in _ad.walk_statements(h.body))
                        if not aborts:
                            out.append((
                                "stage-fallthrough", path, h.lineno,
                                f"{where}:{h.lineno}",
                                f"{where}: stage failure handler at "
                                f"line {h.lineno} neither returns nor "
                                f"raises — a failed stage falls "
                                f"through to the flip phase"))
            staged = _stage_names(fn)
            for n in ast.walk(fn):
                if not isinstance(n, ast.For):
                    continue
                loop_flips = [c for c in ast.walk(n)
                              if isinstance(c, ast.Call) and _is_flip(c)]
                if not loop_flips:
                    continue
                names = _iter_names(n.iter)
                bad = names is None or any(nm not in staged
                                           for nm in names)
                if bad:
                    out.append((
                        "stale-engine-set", path, n.lineno,
                        f"{where}:{n.lineno}",
                        f"{where}: flip loop at line {n.lineno} "
                        f"iterates a set not provably the staged "
                        f"snapshot — an engine registered mid-swap "
                        f"would flip without staging"))
            if not _holds_lock(fn, graph.types, owner):
                out.append((
                    "barrier-unlocked", path, fn.lineno, where,
                    f"{where} coordinates stage+flip without holding "
                    f"a lock (and is not *_locked): registration can "
                    f"interleave between the phases"))
        else:  # flip with no local stage: forwarder or guarded shim
            if key[1] in FORWARDERS:
                continue
            for c, why in flips:
                val = _cg.kwarg(c, "staged")
                if val is not None and _guarded(fn, val, c.lineno):
                    continue
                if val is None and isinstance(c.func, ast.Attribute) \
                        and _guarded(fn, c.func.value, c.lineno):
                    continue
                out.append((
                    "unguarded-flip", path, c.lineno,
                    f"{where}:{why}",
                    f"{where} flips ({why}) with no local stage and no "
                    f"`is None -> raise/return` guard on the staged "
                    f"value: nothing proves staging happened"))
    return out


@register
class SwapBarrierPass(AnalysisPass):
    name = "swap-barrier"
    ir = "ast"
    description = ("no flip unless dominated by stage-all over the "
                   "same engine set; no registration between stage "
                   "and flip")

    def run(self, ctx):
        return [self.finding(rule, path, line, key=key, message=msg)
                for rule, path, line, key, msg in analyze(ctx.ast)]
