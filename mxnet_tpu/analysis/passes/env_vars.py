"""env-vars pass: the env surface and its documentation cannot drift.

Statically collects every ``MXTPU_*``/``MXNET_*`` environment variable
the code actually consults — ``os.environ.get/[]``, ``os.getenv``,
``os.environ.setdefault``, the typed ``base.get_env``/``env_*`` helpers
— across the package, tools, benchmarks and launch entry points, and
diffs it against ``docs/ENV_VARS.md``:

- a variable READ in code but absent from the doc is an undocumented
  knob (operators cannot discover it);
- a variable documented in a TABLE ROW but never consulted anywhere is
  dead documentation (the knob silently stopped existing).

Prefix rows like ``MXTPU_FAULT_<POINT>`` match any var starting with the
prefix; the "n/a by design" prose section is ignored (those names are
documented AS absent). Env WRITES (``os.environ["X"] = ...``) count as
uses — a var one process sets for another to read is part of the
surface.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Set

from ..core import AnalysisPass, REPO_ROOT, register
from .. import ast_driver as _ad

DOC = "docs/ENV_VARS.md"
SCAN_DIRS = ("mxnet_tpu", "tools", "benchmarks")
SCAN_FILES = ("bench.py", "__graft_entry__.py", "tests/conftest.py")
PREFIXES = ("MXTPU_", "MXNET_")

ENV_HELPER_NAMES = {"get_env", "env_bool", "env_int", "env_str",
                    "env_float", "getenv"}


def _is_env_name(s) -> bool:
    return isinstance(s, str) and s.startswith(PREFIXES)


def collect_code_vars(index: _ad.AstIndex) -> Dict[str, List]:
    """var -> [(path, lineno)] for every env consultation with a literal
    MXTPU_/MXNET_ name."""
    out: Dict[str, List] = {}
    files = list(index.package_files(*SCAN_DIRS))
    files += [f for f in SCAN_FILES
              if os.path.exists(os.path.join(index.repo_root, f))]

    def note(name, path, ln):
        out.setdefault(name, []).append((path, ln))

    for rel in files:
        try:
            mod = index.module(rel)
        except SyntaxError:
            continue
        for node in ast.walk(mod.tree):
            # os.environ.get("X") / os.environ.setdefault("X", ...) /
            # os.getenv("X") / get_env("X", ...) / env_int("X", ...)
            if isinstance(node, ast.Call) and node.args:
                name = _ad.dotted(node.func) or ""
                attr = name.rsplit(".", 1)[-1]
                env_call = name.endswith(("environ.get",
                                          "environ.setdefault")) or \
                    attr.lstrip("_") in ENV_HELPER_NAMES
                if env_call and isinstance(node.args[0], ast.Constant) \
                        and _is_env_name(node.args[0].value):
                    note(node.args[0].value, rel, node.lineno)
            # os.environ["X"] (read or write)
            if isinstance(node, ast.Subscript):
                base = _ad.dotted(node.value) or ""
                sl = node.slice
                if base.endswith("environ") and \
                        isinstance(sl, ast.Constant) and \
                        _is_env_name(sl.value):
                    note(sl.value, rel, node.lineno)
            # prefix-style uses: "MXTPU_FAULT_" + point  /
            # name.startswith("MXTPU_...") — recorded with the trailing
            # underscore so they match prefix rows in the doc
            if isinstance(node, ast.BinOp) and \
                    isinstance(node.op, ast.Add) and \
                    isinstance(node.left, ast.Constant) and \
                    _is_env_name(node.left.value) and \
                    node.left.value.endswith("_"):
                note(node.left.value, rel, node.lineno)
            if isinstance(node, ast.Call) and \
                    getattr(node.func, "attr", None) == "startswith" \
                    and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    _is_env_name(node.args[0].value):
                note(node.args[0].value, rel, node.lineno)
    return out


def collect_doc_vars(repo_root: str = REPO_ROOT) -> Dict[str, int]:
    """Documented vars from ENV_VARS.md TABLE ROWS only (the n/a prose
    section documents absence, not presence): var -> line. A name ending
    in ``_<...>`` or ``_*`` is a prefix row."""
    out: Dict[str, int] = {}
    with open(os.path.join(repo_root, DOC)) as f:
        for i, line in enumerate(f, 1):
            if not line.lstrip().startswith("|"):
                continue
            m = re.match(r"\s*\|\s*`([A-Z0-9_*<>]+)`?", line)
            if not m:
                continue
            name = m.group(1)
            name = re.sub(r"<[A-Z_]*>$", "", name).rstrip("*")
            if name.startswith(PREFIXES):
                out.setdefault(name, i)
    return out


def _doc_covers(var: str, doc_vars) -> bool:
    if var in doc_vars:
        return True
    return any(d.endswith("_") and var.startswith(d) for d in doc_vars)


def _code_covers(doc_var: str, code_vars: Set[str]) -> bool:
    if doc_var in code_vars:
        return True
    if doc_var.endswith("_"):  # prefix row
        return any(v.startswith(doc_var) for v in code_vars)
    return False


@register
class EnvVarsPass(AnalysisPass):
    name = "env-vars"
    ir = "meta"
    description = ("every MXTPU_*/MXNET_* env read is documented in "
                   "docs/ENV_VARS.md, and nothing documented is dead")

    def run(self, ctx):
        findings = []
        code = collect_code_vars(ctx.ast)
        doc = collect_doc_vars(ctx.repo_root)
        for var in sorted(code):
            if not _doc_covers(var, doc):
                path, ln = code[var][0]
                findings.append(self.finding(
                    "undocumented", path, ln, key=var,
                    message=f"env var {var} is consulted at {path}:{ln} "
                    f"(+{len(code[var]) - 1} more) but has no row in "
                    f"{DOC} — operators cannot discover it"))
        for var, ln in sorted(doc.items()):
            if not _code_covers(var, set(code)):
                findings.append(self.finding(
                    "dead-doc", DOC, ln, key=var,
                    message=f"env var {var} is documented ({DOC}:{ln}) "
                    "but nothing in the package/tools consults it — "
                    "dead documentation (remove the row or restore the "
                    "knob)"))
        return findings
