"""collective-placement pass: collectives live where the design says.

Two rules:

- **decode-collective** (jaxpr) — the default serving layout (replicated
  params, meshless engine) must dispatch ZERO collectives in the decode
  programs (dense ``decode_n`` loop and paged ``decode_iter``): a
  ``psum``/``all_gather`` smuggled into sampling or attention turns
  every O(1) decode step into a cross-device barrier. (FSDP serving
  legitimately gathers — that layout is exercised by the sharding tests;
  this rule pins the DEFAULT path.)
- **host-allreduce-guard** (AST) — the host-side gradient allreduce
  (``Trainer._allreduce_grads`` KVStore loop, ``KVStoreDist`` push) must
  never be reachable when the process-global mesh spans every worker:
  in-graph psum owns gradient sync there, and the host loop double-sums
  on top of it. The ``mesh_spans_processes()`` guard (PR 6) must
  dominate both sites.
"""

from __future__ import annotations

import ast
from typing import List, Tuple

from ..core import AnalysisPass, register

INFER_PY = "mxnet_tpu/parallel/infer.py"
TRAINER_PY = "mxnet_tpu/gluon/trainer.py"
KVDIST_PY = "mxnet_tpu/kvstore/kvstore_dist.py"

COLLECTIVE_PRIMITIVES = {
    "psum", "psum2", "all_gather", "all_reduce", "reduce_scatter",
    "all_to_all", "ppermute", "pmin", "pmax", "pgather",
}

# (path, class, method, how) — how = "return-guard" (an
# `if mesh_spans_processes(...): ... return` must appear before the
# collective work) or "call-guard" (a guard helper must be called)
GUARD_SITES = (
    (TRAINER_PY, "Trainer", "_allreduce_grads", "return-guard"),
    (KVDIST_PY, "KVStoreDist", "_push_impl", "call-guard"),
)
GUARD_NAMES = ("mesh_spans_processes", "_warn_if_mesh_owns_sync")


def check_decode_collectives(programs) -> List[str]:
    from .. import jaxpr_driver as _jd

    msgs = []
    _, decode_jaxpr, _, _ = programs.decode_programs()
    _, decode_iter_jaxpr, _, _ = programs.paged_programs()
    for label, jaxpr in (("decode_n loop", decode_jaxpr),
                        ("decode_iter", decode_iter_jaxpr)):
        hit = _jd.primitive_names(jaxpr) & COLLECTIVE_PRIMITIVES
        if hit:
            msgs.append(
                f"InferStep {label}: collective primitive(s) "
                f"{sorted(hit)} in the default (meshless) decode "
                "program — every decode step becomes a cross-device "
                "barrier")
    return msgs


def check_host_allreduce_guard(index, sites=GUARD_SITES) -> List[Tuple]:
    out = []
    for path, cls_name, meth, how in sites:
        mod = index.module(path)
        cls = mod.classes.get(cls_name)
        fn = None
        if cls is not None:
            for n in cls.body:
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and n.name == meth:
                    fn = n
        if fn is None:
            out.append((0, f"{cls_name}.{meth}:missing",
                        f"{path}: {cls_name}.{meth} not found — update "
                        "the collective-placement pass if the host "
                        "allreduce moved"))
            continue
        guarded = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = getattr(node.func, "attr", None) or \
                    getattr(node.func, "id", None)
                if name in GUARD_NAMES:
                    if how == "call-guard":
                        guarded = True
            if how == "return-guard" and isinstance(node, ast.If):
                test_calls = [getattr(c.func, "attr", None)
                              or getattr(c.func, "id", None)
                              for c in ast.walk(node.test)
                              if isinstance(c, ast.Call)]
                if any(n in GUARD_NAMES for n in test_calls) and any(
                        isinstance(s, ast.Return)
                        for s in ast.walk(node)):
                    guarded = True
        if not guarded:
            out.append((
                fn.lineno, f"{cls_name}.{meth}:unguarded",
                f"{path}: {cls_name}.{meth} runs the host allreduce "
                "path without a mesh_spans_processes() guard — when the "
                "mesh spans every process, in-graph psum already owns "
                "gradient sync and this double-sums"))
    return out


@register
class CollectivePlacementPass(AnalysisPass):
    name = "collective-placement"
    ir = "jaxpr"
    description = ("no collectives in the default decode programs; host "
                   "allreduce gated on mesh_spans_processes()")

    def run(self, ctx):
        findings = []
        for ln, key, msg in check_host_allreduce_guard(ctx.ast):
            findings.append(self.finding(
                "host-allreduce-guard",
                msg.split(":")[0], ln, key=key, message=msg))
        for msg in check_decode_collectives(ctx.programs):
            findings.append(self.finding(
                "decode-collective", INFER_PY, 0, key=msg[:80],
                message=msg))
        return findings
