"""no-sync pass: the jitted hot paths must never block on the device.

Port of ``tools/check_no_sync_in_step.py`` (PR 2/5/8) onto the pass
framework — same rule sets, same targets, same assertions. Any host
synchronization (``.asnumpy()``, ``float(loss)``, ``np.asarray`` on a
device array, ``block_until_ready``, ``time.sleep``) inside a dispatch
path silently serializes the pipeline against the device; this walks the
AST of the listed (file, class, methods) targets and flags blocking
calls. The tool remains as a thin CLI shim importing from here.
"""

from __future__ import annotations

import ast
import os

from ..core import AnalysisPass, REPO_ROOT, register

STEP_PY = "mxnet_tpu/parallel/step.py"
INFER_PY = "mxnet_tpu/parallel/infer.py"
BATCHER_PY = "mxnet_tpu/serving/batcher.py"

# the train-step fast-path bodies: __call__ (DeviceBatch detection +
# dispatch) and _dispatch (the staged-operand hot dispatch). _stage is
# deliberately NOT linted — it is the slow path the fast path skips.
FAST_PATH_FUNCS = ("__call__", "_dispatch")

# every linted (file, class, methods) hot path. The inference engine's
# decode_n is the whole generation dispatch and decode_iter/prefill_paged
# are the continuous-batching iteration dispatches; the batchers'
# _dispatch methods assemble and fire batches (DynamicBatcher._resolve /
# ContinuousBatcher._collect+_admit are the designated sync points and
# stay unlinted). ContinuousBatcher._step_once — the scheduler loop body
# — is linted too: its syncs must stay delegated to those named phases,
# never inlined next to a dispatch.
TARGETS = (
    (STEP_PY, "TrainStep", FAST_PATH_FUNCS),
    (INFER_PY, "InferStep", ("__call__", "_dispatch", "decode_n",
                             "decode_iter", "prefill_paged",
                             "prefill_suffix_paged", "spec_draft",
                             "spec_verify")),
    (BATCHER_PY, "DynamicBatcher", ("_dispatch",)),
    (BATCHER_PY, "ContinuousBatcher", ("_dispatch", "_step_once")),
)

# method attributes that force a device->host readback / host sync
BLOCKING_ATTRS = {
    "asnumpy", "asscalar", "item", "tolist", "block_until_ready",
    "copy_to_host_async",
}
# bare builtins that coerce a device scalar on the host
BLOCKING_BUILTINS = {"float", "int", "bool", "complex", "print"}
# module.attr calls that materialize device arrays on host (np.asarray on
# a device array round-trips it) or stall the thread
BLOCKING_QUALIFIED = {
    ("np", "asarray"), ("_np", "asarray"), ("numpy", "asarray"),
    ("np", "array"), ("_np", "array"), ("numpy", "array"),
    ("jax", "device_get"), ("time", "sleep"), ("_time", "sleep"),
}


def blocking_calls_in(fn: ast.FunctionDef, label: str):
    """[(lineno, message)] for blocking calls anywhere in ``fn``."""
    out = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id in BLOCKING_BUILTINS:
            out.append((node.lineno,
                        f"{label}: host coercion {f.id}(...) blocks on "
                        "the device value"))
        elif isinstance(f, ast.Attribute):
            if f.attr in BLOCKING_ATTRS:
                out.append((node.lineno,
                            f"{label}: .{f.attr}() forces a device->host "
                            "sync"))
            elif isinstance(f.value, ast.Name) and \
                    (f.value.id, f.attr) in BLOCKING_QUALIFIED:
                out.append((node.lineno,
                            f"{label}: {f.value.id}.{f.attr}(...) "
                            "materializes/stalls on host"))
    return out


def find_violations(path=None, class_name: str = "TrainStep",
                    funcs=FAST_PATH_FUNCS):
    """Return [(lineno, message)] for blocking calls inside the given
    class's listed method bodies (tool-compatible entry point; ``path``
    may be absolute or repo-relative)."""
    if path is None:
        path = os.path.join(REPO_ROOT, STEP_PY)
    elif not os.path.isabs(path):
        path = os.path.join(REPO_ROOT, path)
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    out = []
    classes = [n for n in tree.body
               if isinstance(n, ast.ClassDef) and n.name == class_name]
    if not classes:
        return [(0, f"{class_name} class not found in {path}")]
    fns = [n for n in classes[0].body
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
           and n.name in funcs]
    missing = set(funcs) - {f.name for f in fns}
    if missing:
        out.append((classes[0].lineno,
                    f"{class_name} hot-path method(s) {sorted(missing)} "
                    "not found — update TARGETS if the hot path was "
                    "renamed"))
    for fn in fns:
        out.extend(blocking_calls_in(fn, f"{class_name}.{fn.name}"))
    return sorted(out)


def find_all_violations():
    """Lint every TARGETS entry; returns [(path, lineno, message)]."""
    out = []
    for path, cls, funcs in TARGETS:
        for lineno, msg in find_violations(path, cls, funcs):
            out.append((path, lineno, msg))
    return out


@register
class NoSyncPass(AnalysisPass):
    name = "no-sync"
    ir = "ast"
    description = ("jitted train/inference/serving hot paths stay free "
                   "of blocking host syncs")

    def run(self, ctx):
        findings = []
        for path, cls, funcs in TARGETS:
            for lineno, msg in find_violations(path, cls, funcs):
                findings.append(self.finding(
                    "blocking-call", path, lineno,
                    key=msg.split(":")[0] + ":" + msg.split(":", 2)[-1][:60],
                    message=msg))
        return findings
