"""mxlint: the unified static-analysis framework.

One pass registry over the two IRs this repo already lints — Python AST
for host code and jaxpr for the jitted programs — replacing the three
ad-hoc checkers (no-sync, AMP purity, sharding placement) that grew one
per PR. Every checker is an ``AnalysisPass`` producing ``Finding``\\ s
with stable fingerprints; pre-existing violations live in a committed
baseline file with a reason each, so the suite runs green at HEAD while
new violations fail CI.

Entry points:

- ``python tools/mxlint.py [--json]`` — the CLI (all passes, baseline
  applied, JSON for CI);
- ``tests/test_mxlint.py`` — the tier-1 wiring (full suite green +
  violation self-tests per pass);
- ``mxnet_tpu.analysis.run_passes()`` — programmatic.

See docs/ARCHITECTURE.md "Static analysis" for the pass list and how to
add a pass.
"""

from .core import (  # noqa: F401
    AnalysisPass, Baseline, Context, Finding, Severity, all_passes,
    get_pass, register, run_passes,
)

__all__ = ["AnalysisPass", "Baseline", "Context", "Finding", "Severity",
           "all_passes", "get_pass", "register", "run_passes"]
