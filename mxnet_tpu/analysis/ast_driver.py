"""AST module walker with symbol resolution — the host-code IR driver.

Provides cached parsing plus the resolution primitives every AST pass
shares:

- ``AstIndex`` — repo-relative module cache (``index.module("mxnet_tpu/
  serving/batcher.py")``), class table with base-class resolution across
  a module set (``classes_in``), and source access for messages;
- ``dotted(expr)`` — best-effort dotted name of an expression
  (``self._engine.decode_iter``, ``time.sleep``) so rule sets can match
  call shapes without chasing objects;
- ``FunctionModel`` — per-function facts passes keep re-deriving: the
  ordered statement walk, call sites, ``self.X`` loads/stores, and the
  ``with self.<lock>`` structure.

Resolution is deliberately *intra-module + declared bases*: precise
enough for the package's code shapes, cheap enough to run in tier-1.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from .core import REPO_ROOT


def dotted(expr) -> Optional[str]:
    """Dotted name of a Name/Attribute chain; None for anything fancier
    (subscripts, calls) — callers treat None as 'unknown receiver'."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return None


def self_attr(expr) -> Optional[str]:
    """'X' when ``expr`` is exactly ``self.X``, else None."""
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and expr.value.id == "self":
        return expr.attr
    return None


def walk_statements(body: Sequence[ast.stmt]) -> Iterator[ast.stmt]:
    """Yield statements in source order, recursing into compound bodies
    (the linear over-approximation the dataflow passes use)."""
    for stmt in body:
        yield stmt
        for field in ("body", "orelse", "finalbody"):
            inner = getattr(stmt, field, None)
            if inner:
                yield from walk_statements(inner)
        for handler in getattr(stmt, "handlers", []) or []:
            yield from walk_statements(handler.body)


class Module:
    def __init__(self, path: str, tree: ast.Module, source: str):
        self.path = path
        self.tree = tree
        self.source = source
        self.classes: Dict[str, ast.ClassDef] = {
            n.name: n for n in tree.body if isinstance(n, ast.ClassDef)}
        self.functions: Dict[str, ast.FunctionDef] = {
            n.name: n for n in tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


class ClassModel:
    """A class with inheritance flattened over the analyzed module set:
    ``methods`` maps name -> (FunctionDef, defining Module)."""

    def __init__(self, name: str, module: Module):
        self.name = name
        self.module = module
        self.methods: Dict[str, Tuple[ast.FunctionDef, Module]] = {}
        self.node = module.classes[name]

    def method(self, name: str) -> Optional[ast.FunctionDef]:
        entry = self.methods.get(name)
        return entry[0] if entry else None


class AstIndex:
    """Parse-once module cache keyed by repo-relative path."""

    def __init__(self, repo_root: str = REPO_ROOT):
        self.repo_root = repo_root
        self._cache: Dict[str, Module] = {}

    def module(self, rel_path: str) -> Module:
        rel_path = rel_path.replace(os.sep, "/")
        m = self._cache.get(rel_path)
        if m is None:
            path = os.path.join(self.repo_root, rel_path)
            with open(path) as f:
                source = f.read()
            m = Module(rel_path, ast.parse(source, filename=path), source)
            self._cache[rel_path] = m
        return m

    def package_files(self, *subdirs: str) -> List[str]:
        """Every .py under the given repo-relative directories."""
        out = []
        for sub in subdirs:
            root = os.path.join(self.repo_root, sub)
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        rel = os.path.relpath(os.path.join(dirpath, fn),
                                              self.repo_root)
                        out.append(rel.replace(os.sep, "/"))
        return out

    def classes_in(self, rel_paths: Sequence[str]) -> Dict[str, ClassModel]:
        """Class table over a module set with single-inheritance
        flattening: a subclass's method table is its bases' (resolved by
        bare name anywhere in the set) overlaid with its own."""
        modules = [self.module(p) for p in rel_paths]
        by_name: Dict[str, Tuple[ast.ClassDef, Module]] = {}
        for m in modules:
            for cname, cnode in m.classes.items():
                by_name[cname] = (cnode, m)
        models: Dict[str, ClassModel] = {}

        def build(cname: str) -> Optional[ClassModel]:
            if cname in models:
                return models[cname]
            if cname not in by_name:
                return None
            cnode, m = by_name[cname]
            model = ClassModel(cname, m)
            models[cname] = model  # break cycles defensively
            for base in cnode.bases:
                bname = base.id if isinstance(base, ast.Name) else None
                if bname:
                    bmodel = build(bname)
                    if bmodel:
                        model.methods.update(bmodel.methods)
            for n in cnode.body:
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    model.methods[n.name] = (n, m)
            return model

        for cname in list(by_name):
            build(cname)
        return models


class FunctionModel:
    """Pre-digested per-function facts for the concurrency passes."""

    def __init__(self, fn: ast.FunctionDef, module: Module):
        self.fn = fn
        self.module = module
        self.calls: List[ast.Call] = [
            n for n in ast.walk(fn) if isinstance(n, ast.Call)]
        self.self_calls: List[str] = []
        for c in self.calls:
            attr = self_attr(c.func)
            if attr is not None:
                self.self_calls.append(attr)

    def self_stores(self) -> List[Tuple[str, int, str]]:
        """(attr, lineno, kind) for every write to ``self.X`` state:
        plain/aug assignment, subscript stores (``self.X[k] = v``,
        ``self.X[k] += v``) and known in-place container mutations
        (``self.X.append(...)``...)."""
        out = []
        mutators = {"append", "appendleft", "extend", "pop", "popleft",
                    "clear", "insert", "remove", "update", "add",
                    "setdefault", "sort", "reverse"}
        def flat_targets(t):
            if isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    yield from flat_targets(e)
            elif isinstance(t, ast.Starred):
                yield from flat_targets(t.value)
            else:
                yield t

        for node in ast.walk(self.fn):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t0 in targets:
                for t in flat_targets(t0):
                    base = t
                    kind = "assign"
                    if isinstance(t, ast.Subscript):
                        base = t.value
                        kind = "subscript"
                    attr = self_attr(base)
                    if attr is not None:
                        out.append((attr, node.lineno, kind))
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in mutators:
                attr = self_attr(node.func.value)
                if attr is not None:
                    out.append((attr, node.lineno, "mutate"))
        return out

    def self_loads(self) -> List[Tuple[str, int, bool]]:
        """(attr, lineno, iterated) for reads of ``self.X``; ``iterated``
        marks reads that traverse the value (for-loops, ``sorted``/
        ``list``/``max``/comprehension iterables) — the reads a
        concurrent mutation actually corrupts."""
        iterating_fns = {"sorted", "list", "tuple", "set", "max", "min",
                         "sum", "any", "all", "len"}
        out = []
        iter_nodes = set()
        for node in ast.walk(self.fn):
            if isinstance(node, (ast.For, ast.comprehension)):
                iter_nodes.add(id(node.iter))
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id in iterating_fns and node.args:
                if not (node.func.id == "len"):
                    iter_nodes.add(id(node.args[0]))
        for node in ast.walk(self.fn):
            attr = self_attr(node)
            if attr is not None and isinstance(node.ctx, ast.Load):
                out.append((attr, node.lineno, id(node) in iter_nodes))
        return out
