"""Project call graph + per-function summaries — mxlint's interprocedural
layer (the jump lock-order made per-class, generalized across classes).

PR 9's passes are per-function/per-module; the serving plane's hardest
bug class is cross-function: a page acquired in ``_stage_slot`` leaking
on an exception three frames up, a worker-verb error path that never
fails the future it registered. In the spirit of compositional analyses
(RacerD/Pulse), this module computes cheap per-function *summaries* and
composes them over a resolved call graph instead of exploring paths:

- ``TypeTable`` — best-effort nominal types: ``self.X = ClassName(...)``
  constructor assignments, ``self.X = param`` where the ``__init__``
  parameter is annotated, and ``-> ClassName`` return annotations. Enough
  to resolve ``self._client.submit(...)`` -> ``RpcClient.submit`` and
  ``self._peer(addr).call(...)`` -> ``RpcClient.call``.
- ``FnInfo`` — per-function exception structure: every node's enclosing
  ``try`` chain (try-body nesting only: handlers/else/finally re-raise
  past their own clauses) and whether a raise at a node is consumed
  inside the function (a handler "consumes" only if its clause matches —
  broadly, or by exception class name — AND its body never re-raises).
- ``ProjectGraph`` — the composition: one node per (class, method) over
  ``AstIndex.classes_in`` (plus module-level functions), call edges
  resolved through the type table, ``threading.Thread(target=self.X)``
  worker entries, and a ``may_raise`` interprocedural fixed point whose
  base facts are explicit ``raise`` statements plus attribute-matched
  contract raisers (``adopt_ref``/``cache_acquire``/fault-point
  ``fire``/``Thread.start``/...). ``escaping_points`` lists the concrete
  statements where an exception can leave a function — the exception
  edges the resource-leak dataflow runs over.

Resolution limits (deliberate, documented): locals are untyped unless
bound by an annotated parameter; unresolved external calls are assumed
non-raising unless attribute-matched. Precise enough for this package's
code shapes, cheap enough for tier-1.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import ast_driver as _ad

# (owner, function): owner is a class name, or the module's repo-relative
# path for module-level functions (class names never contain "/").
NodeKey = Tuple[str, str]

# attribute-name-matched calls that raise by contract, receiver-agnostic:
# PagePool adoption and prefix-trie refcounts raise on misuse, frame
# unpack raises on torn pushes, sharded checkpoint load raises on missing
# shards, Thread.start raises at spawn limits, and armed fault points
# raise FaultInjected — the deterministic "this can fail here" markers
# the serving plane is built around.
RAISING_ATTRS = frozenset({
    "adopt_ref", "cache_acquire", "cache_release", "unpack_frames",
    "load_sharded", "start",
})
RAISING_DOTTED_SUFFIXES = ("faults.fire",)

BUILTIN_ITER_FNS = frozenset({"zip", "enumerate", "list", "sorted",
                              "reversed", "tuple", "set"})


def str_arg(call: ast.Call, i: int = 0) -> Optional[str]:
    """The i-th positional argument when it is a string literal."""
    if len(call.args) > i and isinstance(call.args[i], ast.Constant) \
            and isinstance(call.args[i].value, str):
        return call.args[i].value
    return None


def kwarg(call: ast.Call, name: str):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def receiver_name(expr) -> Optional[str]:
    """Normalized receiver of a method call: ``self.pool`` -> "pool",
    ``self.a.b`` -> "a.b", bare ``name`` -> "name"."""
    d = _ad.dotted(expr)
    if d is None:
        return None
    return d[5:] if d.startswith("self.") else d


def handler_catches(handler: ast.ExceptHandler,
                    exc_name: Optional[str]) -> bool:
    """True when this handler fully consumes an exception of (possibly
    unknown) class ``exc_name``: the clause matches — broadly, or by the
    raised class's bare name — AND the body never re-raises."""
    for stmt in _ad.walk_statements(handler.body):
        if isinstance(stmt, ast.Raise):
            return False
    t = handler.type
    if t is None:
        return True
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in elts:
        d = _ad.dotted(e)
        base = d.rsplit(".", 1)[-1] if d else None
        if base in ("Exception", "BaseException"):
            return True
        if exc_name is not None and base == exc_name:
            return True
    return False


class FnInfo:
    """Per-function exception structure: the enclosing-``try`` chain of
    every node in THIS frame (nested def/lambda bodies raise at call
    time, not here, and are excluded)."""

    def __init__(self, fn):
        self.fn = fn
        self.nodes: List[ast.AST] = []
        self.enclosing: Dict[int, Tuple[ast.Try, ...]] = {}
        self._visit(fn, ())

    def _visit(self, node, stack):
        self.nodes.append(node)
        self.enclosing[id(node)] = stack
        if isinstance(node, ast.Try):
            for c in node.body:
                self._visit(c, stack + (node,))
            for c in node.orelse:
                self._visit(c, stack)
            for h in node.handlers:
                for c in h.body:
                    self._visit(c, stack)
            for c in node.finalbody:
                self._visit(c, stack)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not self.fn:
            return
        for c in ast.iter_child_nodes(node):
            self._visit(c, stack)

    def tries_of(self, node) -> Tuple[ast.Try, ...]:
        return self.enclosing.get(id(node), ())

    def caught(self, node, exc_name: Optional[str] = None) -> bool:
        """True when an exception raised at ``node`` is consumed inside
        this function (some enclosing try has a matching, non-re-raising
        handler)."""
        return any(handler_catches(h, exc_name)
                   for t in self.tries_of(node) for h in t.handlers)

    def calls(self) -> List[ast.Call]:
        return [n for n in self.nodes if isinstance(n, ast.Call)]


class TypeTable:
    """Nominal attr/return types over a class set (see module doc)."""

    def __init__(self, classes: Dict[str, _ad.ClassModel]):
        self.classes = classes
        self.attr_class: Dict[Tuple[str, str], str] = {}
        self.attr_ctor: Dict[Tuple[str, str], ast.Call] = {}
        self.returns: Dict[Tuple[str, str], str] = {}
        for cname, model in classes.items():
            for mname, (fn, _mod) in model.methods.items():
                self._scan_method(cname, mname, fn)

    def _known(self, expr) -> Optional[str]:
        d = _ad.dotted(expr) if expr is not None else None
        base = d.rsplit(".", 1)[-1] if d else None
        return base if base in self.classes else None

    def _scan_method(self, cname, mname, fn):
        ret = self._known(fn.returns)
        if ret:
            self.returns[(cname, mname)] = ret
        ann: Dict[str, str] = {}
        for a in fn.args.args + fn.args.kwonlyargs:
            t = self._known(a.annotation)
            if t:
                ann[a.arg] = t
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                attr = _ad.self_attr(t)
                if attr is None:
                    continue
                if isinstance(node.value, ast.Call):
                    self.attr_ctor.setdefault((cname, attr), node.value)
                    known = self._known(node.value.func)
                    if known:
                        self.attr_class.setdefault((cname, attr), known)
                elif isinstance(node.value, ast.Name) and \
                        node.value.id in ann:
                    self.attr_class.setdefault((cname, attr),
                                               ann[node.value.id])

    def expr_class(self, owner: Optional[str], expr) -> Optional[str]:
        """Best-effort class of ``expr`` inside ``owner``'s methods."""
        if isinstance(expr, ast.Name):
            return owner if expr.id == "self" else None
        if isinstance(expr, ast.Attribute):
            base = self.expr_class(owner, expr.value)
            if base is None:
                return None
            return self.attr_class.get((base, expr.attr))
        if isinstance(expr, ast.Call):
            f = expr.func
            if isinstance(f, ast.Attribute):
                fowner = self.expr_class(owner, f.value)
                if fowner is not None:
                    return self.returns.get((fowner, f.attr))
            return None
        return None

    def ctor_dotted(self, cls: str, attr: str) -> Optional[str]:
        call = self.attr_ctor.get((cls, attr))
        return _ad.dotted(call.func) if call is not None else None


class FnNode:
    """One call-graph node: a method (owner = class name) or module
    function (owner = module path)."""

    __slots__ = ("key", "owner", "name", "fn", "module", "info", "calls")

    def __init__(self, owner: str, name: str, fn, module):
        self.key: NodeKey = (owner, name)
        self.owner = owner
        self.name = name
        self.fn = fn
        self.module = module
        self.info = FnInfo(fn)
        # (ast.Call, resolved callee NodeKey or None), filled by the graph
        self.calls: List[Tuple[ast.Call, Optional[NodeKey]]] = []


class ProjectGraph:
    """The composed interprocedural model over a module set."""

    def __init__(self, index: _ad.AstIndex, rel_paths: Sequence[str],
                 raising_attrs=RAISING_ATTRS):
        self.index = index
        self.rel_paths = [p.replace("\\", "/") for p in rel_paths]
        self.modules = [index.module(p) for p in self.rel_paths]
        self.classes = index.classes_in(self.rel_paths)
        self.types = TypeTable(self.classes)
        self.raising_attrs = set(raising_attrs)
        self.nodes: Dict[NodeKey, FnNode] = {}
        for cname, model in self.classes.items():
            for mname, (fn, mod) in model.methods.items():
                self.nodes[(cname, mname)] = FnNode(cname, mname, fn, mod)
        for mod in self.modules:
            for fname, fn in mod.functions.items():
                self.nodes[(mod.path, fname)] = FnNode(mod.path, fname,
                                                       fn, mod)
        self.callers: Dict[NodeKey, List[Tuple[NodeKey, ast.Call]]] = {}
        self.thread_entries: Set[NodeKey] = set()
        self._resolve()
        self._base_escapes: Dict[NodeKey, list] = {}
        self._may_raise: Dict[NodeKey, bool] = {}
        self._fixed_point()

    # ------------------------------------------------------------ resolution
    def resolve_call(self, owner_cls: Optional[str], module,
                     call: ast.Call) -> Optional[NodeKey]:
        f = call.func
        if isinstance(f, ast.Name):
            if f.id in module.functions:
                return (module.path, f.id)
            return None
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name) and f.value.id == "self" \
                    and owner_cls is not None:
                model = self.classes.get(owner_cls)
                if model is not None and f.attr in model.methods:
                    return (owner_cls, f.attr)
                return None
            t = self.types.expr_class(owner_cls, f.value)
            if t is not None:
                model = self.classes.get(t)
                if model is not None and f.attr in model.methods:
                    return (t, f.attr)
        return None

    def _resolve(self):
        for node in self.nodes.values():
            owner_cls = node.owner if node.owner in self.classes else None
            for c in node.info.calls():
                callee = self.resolve_call(owner_cls, node.module, c)
                node.calls.append((c, callee))
                if callee is not None:
                    self.callers.setdefault(callee, []).append(
                        (node.key, c))
                if _ad.dotted(c.func) == "threading.Thread":
                    tgt = kwarg(c, "target")
                    t = _ad.self_attr(tgt) if tgt is not None else None
                    if t is not None and owner_cls is not None and \
                            (owner_cls, t) in self.nodes:
                        self.thread_entries.add((owner_cls, t))
                    elif isinstance(tgt, ast.Name) and \
                            (node.module.path, tgt.id) in self.nodes:
                        self.thread_entries.add((node.module.path, tgt.id))

    def callers_of(self, key: NodeKey):
        return self.callers.get(key, [])

    # ------------------------------------------------------------ may-raise
    def _raise_sources(self, node: FnNode):
        """(ast node, exc class name or None, description) for every
        potential raise point in the function's own frame."""
        out = []
        for n in node.info.nodes:
            if isinstance(n, ast.Raise):
                e = n.exc
                d = None
                if isinstance(e, ast.Call):
                    d = _ad.dotted(e.func)
                elif e is not None:
                    d = _ad.dotted(e)
                name = d.rsplit(".", 1)[-1] if d else None
                out.append((n, name,
                            f"raise {name}" if name else "re-raise"))
            elif isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute):
                d = _ad.dotted(n.func) or ""
                if n.func.attr in self.raising_attrs:
                    out.append((n, None,
                                f".{n.func.attr}(...) raises by contract"))
                elif d.endswith(RAISING_DOTTED_SUFFIXES):
                    out.append((n, None, f"{d}(...) fault point"))
        return out

    def _fixed_point(self):
        for key, node in self.nodes.items():
            self._base_escapes[key] = [
                (n, name, desc) for n, name, desc
                in self._raise_sources(node)
                if not node.info.caught(n, name)]
            self._may_raise[key] = bool(self._base_escapes[key])
        changed = True
        while changed:
            changed = False
            for key, node in self.nodes.items():
                if self._may_raise[key]:
                    continue
                for c, callee in node.calls:
                    if callee is not None and self._may_raise.get(callee) \
                            and not node.info.caught(c):
                        self._may_raise[key] = True
                        changed = True
                        break

    def may_raise(self, key: NodeKey) -> bool:
        return self._may_raise.get(key, False)

    def escaping_points(self, key: NodeKey):
        """Concrete points where an exception may leave this function:
        [(lineno, description, ast node)], source order. Own raise
        sources plus un-caught calls into may-raise callees."""
        node = self.nodes[key]
        out = [(n.lineno, desc, n)
               for n, _name, desc in self._base_escapes.get(key, [])]
        for c, callee in node.calls:
            if callee is not None and self.may_raise(callee) \
                    and not node.info.caught(c):
                out.append((c.lineno,
                            f"{callee[0]}.{callee[1]}() may raise", c))
        return sorted(out, key=lambda e: e[0])
