"""Pass registry, finding model and baseline workflow for mxlint.

Design (in the spirit of compositional analyses like RacerD): each
checker is a small ``AnalysisPass`` that walks one IR (Python AST or
jaxpr) through a shared ``Context`` of cached parsed modules and lowered
programs, and reports ``Finding``\\ s. A finding's ``fingerprint`` is
stable across unrelated edits (no line numbers), so a committed baseline
file can grandfather a known violation *with a reason* while any NEW
violation still fails CI.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, Iterable, List, Optional

REPO_ROOT = os.path.normpath(os.path.join(
    os.path.dirname(os.path.abspath(__file__)), os.pardir, os.pardir))


class Severity:
    ERROR = "error"      # breaks the CLI / tier-1 test unless baselined
    WARNING = "warning"  # reported, never fails the run


class Finding:
    """One violation.

    ``key`` is the stable identity component: pass+rule+path+key make the
    fingerprint, deliberately excluding line numbers and message wording
    so a baseline entry survives reformatting. Passes should choose keys
    that name the program point (``ClassName.method:what``)."""

    __slots__ = ("pass_name", "rule", "path", "line", "key", "message",
                 "severity")

    def __init__(self, pass_name: str, rule: str, path: str, line: int,
                 key: str, message: str,
                 severity: str = Severity.ERROR):
        self.pass_name = pass_name
        self.rule = rule
        self.path = os.path.relpath(path, REPO_ROOT) \
            if os.path.isabs(path) else path
        self.line = int(line)
        self.key = key
        self.message = message
        self.severity = severity

    @property
    def fingerprint(self) -> str:
        return f"{self.pass_name}.{self.rule}:{self.path}:{self.key}"

    def to_dict(self) -> dict:
        return {"pass": self.pass_name, "rule": self.rule,
                "path": self.path, "line": self.line, "key": self.key,
                "severity": self.severity, "message": self.message,
                "fingerprint": self.fingerprint}

    def __repr__(self):
        return (f"{self.path}:{self.line}: [{self.pass_name}.{self.rule}] "
                f"{self.message}")


class AnalysisPass:
    """Base class: subclasses set ``name``/``ir``/``description`` and
    implement ``run(ctx) -> list[Finding]``. ``ir`` is ``"ast"``,
    ``"jaxpr"`` or ``"meta"`` (repo-level consistency checks); the CLI
    groups and orders by it (cheap AST passes first)."""

    name: str = ""
    ir: str = "ast"
    description: str = ""

    def run(self, ctx: "Context") -> List[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, rule: str, path: str, line: int, key: str,
                message: str, severity: str = Severity.ERROR) -> Finding:
        return Finding(self.name, rule, path, line, key, message, severity)


_REGISTRY: Dict[str, type] = {}


def register(cls):
    """Class decorator: add an ``AnalysisPass`` subclass to the global
    registry (import ``mxnet_tpu.analysis.passes`` to populate it)."""
    if not cls.name:
        raise ValueError(f"pass {cls.__name__} needs a name")
    _REGISTRY[cls.name] = cls
    return cls


def all_passes() -> Dict[str, type]:
    from . import passes  # noqa: F401 - registration side effect
    return dict(_REGISTRY)


def get_pass(name: str) -> AnalysisPass:
    passes = all_passes()
    if name not in passes:
        raise KeyError(f"unknown pass {name!r}; have {sorted(passes)}")
    return passes[name]()


class Context:
    """Shared state across passes: cached ASTs (``ast_driver``) and
    lowered programs (``jaxpr_driver`` — built lazily, so AST-only runs
    never import jax or trace a model)."""

    def __init__(self, repo_root: str = REPO_ROOT):
        self.repo_root = repo_root
        from . import ast_driver
        self.ast = ast_driver.AstIndex(repo_root)
        self._programs = None

    @property
    def programs(self):
        """Lazily built ``jaxpr_driver.ProgramIndex`` over the REAL
        TrainStep/InferStep programs (shared by every jaxpr pass — the
        expensive trace happens once per run)."""
        if self._programs is None:
            from . import jaxpr_driver
            self._programs = jaxpr_driver.ProgramIndex()
        return self._programs


class Baseline:
    """Committed grandfather list: fingerprint -> reason.

    A finding whose fingerprint is present is *suppressed* (reported
    separately, never failing). Entries must carry a non-empty reason —
    the workflow is "fix it, or explain why it stays"."""

    def __init__(self, entries: Optional[Dict[str, dict]] = None,
                 path: Optional[str] = None):
        self.entries = dict(entries or {})
        self.path = path

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls(path=path)
        with open(path) as f:
            data = json.load(f)
        entries = data.get("entries", {})
        for fp, e in entries.items():
            if not str(e.get("reason", "")).strip():
                raise ValueError(
                    f"baseline entry {fp} has no reason — every "
                    "grandfathered violation must explain itself")
        return cls(entries, path=path)

    def reason(self, finding: Finding) -> Optional[str]:
        e = self.entries.get(finding.fingerprint)
        return e.get("reason") if e else None

    def save(self, path: Optional[str] = None):
        path = path or self.path
        with open(path, "w") as f:
            json.dump({"entries": self.entries}, f, indent=2,
                      sort_keys=True)
            f.write("\n")


def run_passes(names: Optional[Iterable[str]] = None,
               baseline: Optional[Baseline] = None,
               ctx: Optional[Context] = None,
               progress: Optional[Callable[[str], None]] = None):
    """Run the named passes (default: all, AST/meta before jaxpr).

    Returns ``(findings, suppressed)``: unbaselined findings and
    ``(finding, reason)`` pairs the baseline grandfathered."""
    registry = all_passes()
    if names is None:
        order = {"ast": 0, "meta": 1, "jaxpr": 2}
        names = sorted(registry, key=lambda n: (order.get(
            registry[n].ir, 9), n))
    ctx = ctx or Context()
    findings: List[Finding] = []
    suppressed = []
    for name in names:
        p = get_pass(name)
        if progress is not None:
            progress(name)
        for f in p.run(ctx):
            reason = baseline.reason(f) if baseline is not None else None
            if reason is not None:
                suppressed.append((f, reason))
            else:
                findings.append(f)
    return findings, suppressed
