"""jaxpr walker — the jitted-program IR driver.

Lowers the REAL programs the repo serves with (not toy stand-ins): a
tiny-but-complete transformer ``TrainStep`` (AMP + remat, via one real
dispatch — the same warmup signature machinery production uses) and an
``InferStep`` over the same model (dense prefill/decode plus the paged
continuous-batching programs). Passes share one ``ProgramIndex`` through
``Context.programs`` so the expensive traces happen once per lint run.

Also owns the generic jaxpr plumbing every jaxpr pass uses:
``iter_jaxprs`` (recursing into pjit/scan/cond/remat sub-jaxprs),
``iter_eqns`` and ``primitive_names``.
"""

from __future__ import annotations

from typing import Iterator, Set

_LOW = ("bfloat16", "float16")


# ------------------------------------------------------------ jaxpr walking
def iter_jaxprs(obj) -> Iterator:
    """Yield every (sub-)jaxpr reachable from a jaxpr / ClosedJaxpr /
    eqn-params value (pjit, scan, cond, while, remat, custom_vjp...)."""
    if obj is None:
        return
    if hasattr(obj, "jaxpr"):  # ClosedJaxpr
        yield from iter_jaxprs(obj.jaxpr)
        return
    if hasattr(obj, "eqns"):  # Jaxpr
        yield obj
        for eqn in obj.eqns:
            for v in eqn.params.values():
                yield from iter_jaxprs(v)
        return
    if isinstance(obj, (tuple, list)):
        for item in obj:
            yield from iter_jaxprs(item)


def iter_eqns(closed_jaxpr) -> Iterator:
    for jaxpr in iter_jaxprs(closed_jaxpr):
        for eqn in jaxpr.eqns:
            yield eqn


def primitive_names(closed_jaxpr) -> Set[str]:
    return {eqn.primitive.name for eqn in iter_eqns(closed_jaxpr)}


def find_mixed_dots(closed_jaxpr):
    """[(primitive, operand dtypes)] for every dot_general mixing fp32
    with a low-precision operand anywhere in the program — the AMP
    purity rule (an un-cast master weight reached an MXU op)."""
    out = []
    for eqn in iter_eqns(closed_jaxpr):
        if eqn.primitive.name != "dot_general":
            continue
        dts = [str(v.aval.dtype) for v in eqn.invars[:2]
               if hasattr(v.aval, "dtype")]
        if "float32" in dts and any(d in _LOW for d in dts):
            out.append((eqn.primitive.name, tuple(dts)))
    return out


def count_low_precision_dots(closed_jaxpr) -> int:
    n = 0
    for eqn in iter_eqns(closed_jaxpr):
        if eqn.primitive.name == "dot_general" and any(
                str(v.aval.dtype) in _LOW for v in eqn.invars[:2]
                if hasattr(v.aval, "dtype")):
            n += 1
    return n


# ------------------------------------------------------- program builders
def build_train_step(amp="bfloat16", remat="dots_saveable"):
    """A minimal transformer TrainStep exercising the full hot-path
    surface (cast params, fp32-pinned norms, attention + tied-embedding
    dots, donated state), dispatched once so ``_last_avals`` holds the
    real warmup signature."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd, optimizer as opt  # noqa: F401
    from mxnet_tpu.gluon.model_zoo.transformer import TransformerModel
    from mxnet_tpu.ndarray.ndarray import NDArray
    from mxnet_tpu.parallel import TrainStep

    net = TransformerModel(src_vocab=64, tgt_vocab=64, units=16,
                           hidden_size=32, num_layers=1, num_heads=2,
                           max_length=32, dropout=0.0)
    net.initialize(mx.initializer.Xavier())
    net._probe_shapes(nd.zeros((2, 8), dtype="int32"),
                      nd.zeros((2, 8), dtype="int32"))

    class CE:
        def __call__(self, logits, label):
            x = logits.data.astype(jnp.float32)
            logp = jax.nn.log_softmax(x, axis=-1)
            nll = -jnp.take_along_axis(
                logp, label.data.astype(jnp.int32)[..., None], axis=-1)
            return NDArray(nll.mean())

    step = TrainStep(net, CE(), opt.AdamW(learning_rate=1e-4), amp=amp,
                     remat=remat)
    rng = np.random.RandomState(0)
    src = nd.array(rng.randint(0, 64, (2, 8)), dtype="int32")
    tgt = nd.array(rng.randint(0, 64, (2, 8)), dtype="int32")
    lab = nd.array(rng.randint(0, 64, (2, 8)), dtype="int32")
    step(src, tgt, lab)  # populates _last_avals
    return step


def build_infer_engine(max_len=32):
    """A decode- AND paged-capable InferStep over the tiny transformer,
    meshless (the collective-placement pass asserts the default serving
    layout dispatches no collectives in decode)."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon.model_zoo.transformer import TransformerModel
    from mxnet_tpu.parallel.infer import InferStep

    net = TransformerModel(src_vocab=64, tgt_vocab=64, units=16,
                           hidden_size=32, num_layers=1, num_heads=2,
                           max_length=32, dropout=0.0)
    net.initialize(mx.initializer.Xavier())
    net._probe_shapes(nd.zeros((2, 8), dtype="int32"),
                      nd.zeros((2, 8), dtype="int32"))
    return InferStep(net, mesh=None, max_len=max_len)


class ProgramIndex:
    """Lazily built, cached real programs for the jaxpr passes."""

    def __init__(self):
        self._train_step = None
        self._train_jaxpr = None
        self._engine = None
        self._decode = None
        self._paged = None

    @property
    def train_step(self):
        if self._train_step is None:
            self._train_step = build_train_step()
        return self._train_step

    @property
    def train_jaxpr(self):
        if self._train_jaxpr is None:
            import jax
            step = self.train_step
            self._train_jaxpr = jax.make_jaxpr(step._step_fn)(
                *step._last_avals)
        return self._train_jaxpr

    @property
    def infer_engine(self):
        if self._engine is None:
            self._engine = build_infer_engine()
        return self._engine

    def decode_programs(self, max_new=4):
        """(prefill_jaxpr, decode_jaxpr, example-arg tuples) for the
        dense greedy decode path, traced from the engine's real cached
        jitted fns over real prefill state."""
        if self._decode is not None:
            return self._decode
        import jax
        import jax.numpy as jnp
        import numpy as np

        eng = self.infer_engine
        src = np.zeros((2, 8), np.int32)
        vl = np.full((2,), 8, np.int32)
        prime = np.full((2, 1), eng._bos, np.int32)
        key = jax.random.PRNGKey(0)
        temp = jnp.float32(1.0)
        prefill_fn = eng._get_prefill_fn(eng._max_len)
        prefill_args = (eng._values, src, vl, prime, key, temp)
        prefill_jaxpr = jax.make_jaxpr(prefill_fn)(*prefill_args)
        logits, state = prefill_fn(*prefill_args)
        decode_fn = eng._get_decode_fn(max_new, "greedy", 0)
        decode_args = (eng._values, state, logits, jnp.int32(1), key, temp)
        decode_jaxpr = jax.make_jaxpr(decode_fn)(*decode_args)
        self._decode = (prefill_jaxpr, decode_jaxpr,
                        prefill_args, decode_args)
        return self._decode

    def paged_programs(self, slots=2, num_pages=4, page_size=4,
                       mem_len=8, steps=2):
        """(prefill_paged_jaxpr, decode_iter_jaxpr, example args) for the
        continuous-batching programs over a real paged state."""
        if self._paged is not None:
            return self._paged
        import jax
        import jax.numpy as jnp
        import numpy as np

        eng = self.infer_engine
        state = eng.init_paged_state(slots, num_pages, page_size, mem_len)
        src = np.zeros((slots, mem_len), np.int32)
        vl = np.full((slots,), mem_len, np.int32)
        slot_ids = np.arange(slots, dtype=np.int32)
        first_pages = np.ones((slots,), np.int32)
        active = np.ones((slots,), bool)
        key = jax.random.PRNGKey(0)
        temp = jnp.float32(1.0)
        pfn = eng._get_paged_prefill_fn("greedy", 0)
        pargs = (eng._values, state, src, vl, slot_ids, first_pages,
                 active, key, temp)
        prefill_jaxpr = jax.make_jaxpr(pfn)(*pargs)
        tables = np.zeros((slots, 2), np.int32)
        tokens = np.zeros((slots,), np.int32)
        lengths = np.ones((slots,), np.int32)
        dfn = eng._get_decode_iter_fn(steps, "greedy", 0)
        dargs = (eng._values, state, tables, tokens, lengths, active,
                 key, temp)
        decode_jaxpr = jax.make_jaxpr(dfn)(*dargs)
        self._paged = (prefill_jaxpr, decode_jaxpr, pargs, dargs)
        return self._paged
