"""Serialization + misc utilities.

Reference: the ``mx.nd.save/load`` binary format implemented in
``src/ndarray/ndarray.cc`` (magic header, dense+sparse payloads)
[unverified]. TPU-native storage uses the portable ``.npz`` container with a
manifest entry that round-trips list-vs-dict structure; sharded checkpoints
for large models live in ``mxnet_tpu.checkpoint`` (orbax/tensorstore-style).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Union

import numpy as _np

from .base import MXNetError

_MANIFEST_KEY = "__mxnet_tpu_manifest__"


def save_ndarrays(fname: str, data):
    from .ndarray.ndarray import NDArray

    if isinstance(data, NDArray):
        data = [data]
    arrays = {}
    if isinstance(data, dict):
        manifest = {"kind": "dict", "keys": list(data.keys())}
        for i, (k, v) in enumerate(data.items()):
            arrays[f"arr_{i}"] = _np.asarray(v.asnumpy() if isinstance(v, NDArray) else v)
    elif isinstance(data, (list, tuple)):
        manifest = {"kind": "list", "keys": [str(i) for i in range(len(data))]}
        for i, v in enumerate(data):
            arrays[f"arr_{i}"] = _np.asarray(v.asnumpy() if isinstance(v, NDArray) else v)
    else:
        raise MXNetError(f"cannot save type {type(data)}")
    arrays[_MANIFEST_KEY] = _np.frombuffer(
        json.dumps(manifest).encode(), dtype=_np.uint8
    )
    _np.savez(fname if fname.endswith(".npz") else fname, **arrays)
    # numpy appends .npz; normalize to the exact requested name
    if not fname.endswith(".npz") and os.path.exists(fname + ".npz"):
        os.replace(fname + ".npz", fname)


def load_ndarrays(fname: str):
    from .ndarray.ndarray import NDArray

    with _np.load(fname, allow_pickle=False) as z:
        if _MANIFEST_KEY not in z:
            # plain npz from elsewhere: return dict
            return {k: NDArray(z[k]) for k in z.files}
        manifest = json.loads(bytes(z[_MANIFEST_KEY].tobytes()).decode())
        arrays = [NDArray(z[f"arr_{i}"]) for i in range(len(manifest["keys"]))]
    if manifest["kind"] == "dict":
        return dict(zip(manifest["keys"], arrays))
    return arrays


def makedirs(d: str):
    os.makedirs(d, exist_ok=True)


def get_gpu_count():  # legacy helper name
    from .context import num_gpus

    return num_gpus()


def get_gpu_memory(dev_id: int = 0):
    return (0, 0)  # XLA owns HBM; per-buffer stats via profiler


def use_np(func):
    """Decorator kept for API parity (numpy semantics are the default here)."""
    return func


def use_np_shape(func):
    return func


def use_np_array(func):
    return func


def is_np_shape():
    return True


def is_np_array():
    return True


def set_np(shape=True, array=True, dtype=False):
    return None


def reset_np():
    return None
