"""Shape-stable execution: persistent XLA compilation cache + recompile guard.

On TPU the classic failure mode of a variable-shape input pipeline is the
XLA compile storm: every distinct ``(batch, seq_len)`` signature retraces
and recompiles the whole step program, and nothing survives the process,
so elastic restarts and multi-process launches pay the full compile bill
again. This module is the process-level half of the cure (the input-side
half is ``gluon.data.bucketing``; the ahead-of-time half is
``TrainStep.warmup`` / ``CachedOp.warmup``):

- **Persistent compilation cache** — wires JAX's on-disk cache so XLA
  binaries outlive the process. Enabled by default under a conventional
  cache directory (``~/.cache/mxnet_tpu/xla-cache``, honoring
  ``XDG_CACHE_HOME``) with JAX's stock write thresholds (only compiles
  worth caching are written); setting ``MXTPU_COMPILE_CACHE_DIR`` to a
  path pins the directory AND drops the thresholds to zero so *every*
  program is persisted — the elastic-restart / multi-process launch mode
  where the second process must hit, not recompile. ``0``/``off``
  disables entirely.

- **Cache hit/miss telemetry** — a ``jax.monitoring`` event listener
  lands ``compile/cache_hits`` and ``compile/cache_misses`` counters in
  the telemetry registry (always-on: the registry is usable even with
  event emission disabled).

- **RecompileGuard** — per-``TrainStep``/``CachedOp`` signature
  accounting: every distinct operand-aval signature is one XLA program,
  so the guard's counters are exact compile counters without touching
  JAX internals (``compile/signatures``,
  ``compile/steady_state_recompiles``). After warmup marks the guard
  steady, a new signature is an *accidental* recompile: it warns, or
  raises once the count exceeds ``MXTPU_RECOMPILE_LIMIT``.

Env knobs: ``MXTPU_COMPILE_CACHE_DIR`` (path | ``0``/``off`` | unset =
convention dir), ``MXTPU_RECOMPILE_LIMIT`` (unset = warn-only; ``N`` =
raise after N steady-state recompiles; negative = silence the guard).
"""

from __future__ import annotations

import os
import threading
import warnings
from typing import Optional

from . import telemetry as _tel
from .base import MXNetError

__all__ = [
    "setup", "enable", "disable", "is_enabled", "cache_dir", "cache_stats",
    "recompile_limit", "RecompileGuard",
]

_LOCK = threading.RLock()
_ENABLED = False
_DIR: Optional[str] = None
_LISTENER_INSTALLED = False

# signature-count warning threshold when MXTPU_RECOMPILE_LIMIT is unset:
# a staged cache holding more programs than this is almost certainly
# shape churn, not intent
_DEFAULT_SIG_WARN = 32


def _default_dir() -> str:
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache")
    return os.path.join(base, "mxnet_tpu", "xla-cache")


def recompile_limit() -> Optional[int]:
    """``MXTPU_RECOMPILE_LIMIT`` parsed: None when unset/empty (warn-only
    guard), an int otherwise (negative silences the guard entirely)."""
    v = os.environ.get("MXTPU_RECOMPILE_LIMIT", "").strip()
    if not v:
        return None
    try:
        return int(v)
    except ValueError:
        warnings.warn(
            f"MXTPU_RECOMPILE_LIMIT={v!r} is not an integer; ignoring",
            RuntimeWarning)
        return None


# ------------------------------------------------------------- cache wiring
def _install_metrics_listener():
    """Count persistent-cache hit/miss monitoring events into the
    registry. Registration is append-only in jax, so install once."""
    global _LISTENER_INSTALLED
    if _LISTENER_INSTALLED:
        return
    try:
        from jax import monitoring as _mon

        def _on_event(event, **kwargs):
            if event.endswith("/cache_hits"):
                _tel.registry().counter("compile/cache_hits").inc()
            elif event.endswith("/cache_misses"):
                _tel.registry().counter("compile/cache_misses").inc()

        _mon.register_event_listener(_on_event)
        _LISTENER_INSTALLED = True
    except Exception:  # noqa: BLE001 - jax without monitoring
        _LISTENER_INSTALLED = True  # don't retry every enable()


def enable(directory: Optional[str] = None,
           min_compile_time_secs: Optional[float] = None,
           min_entry_size_bytes: Optional[int] = None) -> str:
    """Point JAX's persistent compilation cache at ``directory`` (created
    on demand by jax) and install the hit/miss counters. Threshold args
    of None keep jax's defaults (write only compiles that took >= 1s) —
    pass 0 to persist everything (what an explicit
    ``MXTPU_COMPILE_CACHE_DIR`` does)."""
    global _ENABLED, _DIR
    import jax

    with _LOCK:
        directory = directory or _default_dir()
        jax.config.update("jax_compilation_cache_dir", directory)
        if min_compile_time_secs is not None:
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              float(min_compile_time_secs))
        if min_entry_size_bytes is not None:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              int(min_entry_size_bytes))
        _install_metrics_listener()
        _ENABLED = True
        _DIR = directory
        _tel.registry().gauge("compile/persistent_cache_enabled").set(1)
    return directory


def disable():
    global _ENABLED, _DIR
    import jax

    with _LOCK:
        jax.config.update("jax_compilation_cache_dir", None)
        _ENABLED = False
        _DIR = None
        _tel.registry().gauge("compile/persistent_cache_enabled").set(0)


def is_enabled() -> bool:
    return _ENABLED


def cache_dir() -> Optional[str]:
    return _DIR


def setup():
    """Import-time wiring from ``MXTPU_COMPILE_CACHE_DIR``:

    - unset        -> convention dir, jax's stock write thresholds
    - ``0``/``off``/``false`` -> disabled
    - a path       -> that dir, thresholds dropped to zero (persist all)
    """
    v = os.environ.get("MXTPU_COMPILE_CACHE_DIR")
    try:
        if v is None:
            enable(_default_dir())
        elif v.strip().lower() in ("0", "off", "false", "none", ""):
            return
        else:
            enable(v, min_compile_time_secs=0.0, min_entry_size_bytes=0)
    except Exception as e:  # noqa: BLE001 - cache must never block import
        warnings.warn(
            f"persistent compilation cache setup failed ({e}); continuing "
            "without it", RuntimeWarning)


def cache_stats() -> dict:
    """Persistent-cache status + hit/miss counters (process lifetime)."""
    snap = _tel.registry().snapshot()["counters"]
    return {
        "enabled": _ENABLED,
        "dir": _DIR,
        "hits": snap.get("compile/cache_hits", 0),
        "misses": snap.get("compile/cache_misses", 0),
    }


# ---------------------------------------------------------- recompile guard
class RecompileGuard:
    """Signature accounting for one staged callable (a ``TrainStep`` or a
    ``CachedOp``): each distinct operand-aval signature is exactly one
    XLA program, so ``signatures`` is a compile counter that needs no JAX
    internals. ``mark_steady()`` (called by ``warmup``) arms the
    shape-churn alarm: a new signature afterwards bumps
    ``compile/steady_state_recompiles`` and warns — or raises once the
    count exceeds ``MXTPU_RECOMPILE_LIMIT``."""

    def __init__(self, name: str):
        self.name = name
        self._sigs: dict = {}  # key -> {count, last_used, aval}
        self._steady = False
        self._steady_recompiles = 0
        self._warned_unbounded = False
        self._seq = 0
        self._lock = threading.Lock()

    # `summary` is a human-readable aval description stored for
    # cache_info(); a callable defers the string build to the (rare)
    # new-signature case so the hot dispatch never pays for it
    def observe(self, key, summary=None) -> bool:
        """Record one dispatch under signature ``key``; returns True when
        the signature is new (== this dispatch compiled)."""
        with self._lock:
            self._seq += 1
            info = self._sigs.get(key)
            if info is not None:
                info["count"] += 1
                info["last_used"] = self._seq
                return False
            if callable(summary):
                summary = summary()
            self._sigs[key] = {
                "count": 1, "last_used": self._seq,
                "aval": summary if summary is not None else str(key),
            }
            n_sigs = len(self._sigs)
            steady = self._steady
            if steady:
                self._steady_recompiles += 1
            n_steady = self._steady_recompiles
        reg = _tel.registry()
        reg.counter("compile/signatures").inc()
        limit = recompile_limit()
        silenced = limit is not None and limit < 0
        if steady:
            reg.counter("compile/steady_state_recompiles").inc()
            if not silenced:
                msg = (
                    f"{self.name}: shape-churn recompile after warmup "
                    f"(new signature {summary}; {n_steady} steady-state "
                    "recompile(s) so far). Pad/bucket inputs to the warmed "
                    "shapes (gluon.data.bucketing) to keep the step loop "
                    "compile-free."
                )
                if limit is not None and n_steady > limit:
                    raise MXNetError(
                        msg + f" MXTPU_RECOMPILE_LIMIT={limit} exceeded.")
                warnings.warn(msg, RuntimeWarning, stacklevel=3)
        bound = limit if limit is not None and limit >= 0 \
            else _DEFAULT_SIG_WARN
        if n_sigs > bound and not self._warned_unbounded and not silenced:
            self._warned_unbounded = True
            warnings.warn(
                f"{self.name} holds {n_sigs} staged signatures (> {bound}) "
                "— each is a separately compiled XLA program held for the "
                "object's lifetime. Bucket or pad inputs "
                "(gluon.data.bucketing) to bound shape churn.",
                RuntimeWarning, stacklevel=3)
        return True

    def mark_steady(self):
        """Declare warmup complete: any new signature from here on is an
        accidental recompile."""
        self._steady = True

    @property
    def steady(self) -> bool:
        return self._steady

    @property
    def signatures(self) -> int:
        return len(self._sigs)

    @property
    def steady_state_recompiles(self) -> int:
        return self._steady_recompiles

    def info(self) -> dict:
        """Per-signature summary: held programs, use counts, recency."""
        with self._lock:
            entries = [
                {"signature": info["aval"], "count": info["count"],
                 "last_used": info["last_used"]}
                for info in self._sigs.values()
            ]
        entries.sort(key=lambda e: -e["last_used"])
        return {
            "name": self.name,
            "signatures": len(entries),
            "steady": self._steady,
            "steady_state_recompiles": self._steady_recompiles,
            "entries": entries,
        }


def normalize_spec(spec):
    """One warmup array spec -> ``(shape tuple, numpy dtype)``.

    Accepts anything with ``.shape``/``.dtype`` (NDArray, jax/numpy
    array, ``jax.ShapeDtypeStruct``) or an explicit ``(shape, dtype)``
    pair."""
    import numpy as _np

    if hasattr(spec, "shape") and hasattr(spec, "dtype"):
        return tuple(spec.shape), _np.dtype(spec.dtype)
    if isinstance(spec, (tuple, list)) and len(spec) == 2:
        shape, dtype = spec
        try:
            return tuple(int(d) for d in shape), _np.dtype(dtype)
        except (TypeError, ValueError):
            pass
    raise MXNetError(
        f"warmup signature entry {spec!r} is not an array, "
        "ShapeDtypeStruct, or (shape, dtype) pair")


def aval_summary(arrays) -> str:
    """Compact ``shape/dtype`` rendering of an operand list for guard
    summaries and ``cache_info``."""
    parts = []
    for a in arrays:
        shape = "x".join(str(d) for d in getattr(a, "shape", ()))
        parts.append(f"{getattr(a, 'dtype', '?')}[{shape}]")
    return "(" + ", ".join(parts) + ")"
