"""AMP op lists (reference: ``python/mxnet/amp/lists/symbol_fp16.py``
[unverified]). Kept as data for API parity; under XLA the lists inform the
cast-insertion in ``convert_model`` rather than namespace monkey-patching."""

# ops that run in the low-precision dtype (MXU-bound)
TARGET_DTYPE_OPS = [
    "Convolution", "Deconvolution", "FullyConnected", "RNN", "dot",
    "batch_dot", "_contrib_interleaved_matmul_selfatt_qk",
    "_contrib_interleaved_matmul_selfatt_valatt",
    "_contrib_interleaved_matmul_encdec_qk",
    "_contrib_interleaved_matmul_encdec_valatt",
    "_contrib_flash_attention",
]

# numerically-sensitive ops pinned to fp32
FP32_OPS = [
    "BatchNorm", "LayerNorm", "InstanceNorm", "GroupNorm", "L2Normalization",
    "LRN", "SoftmaxOutput", "softmax", "log_softmax", "softmax_cross_entropy",
    "exp", "log", "log10", "log2", "log1p", "expm1", "erfinv", "norm",
    "mean", "sum", "prod", "logsumexp",
]

# run in fp32 only when inputs would overflow (reference: conditional list)
CONDITIONAL_FP32_OPS = [
    ("Activation", "act_type", ["softrelu"]),
    ("LeakyReLU", "act_type", ["elu", "selu"]),
]

# everything else: dtype of the widest input
WIDEST_TYPE_CASTS = ["broadcast_add", "broadcast_sub", "broadcast_mul",
                     "broadcast_div", "concat", "where", "stack"]

# Block classes whose PARAMETERS stay fp32 under TrainStep AMP (the
# cast-insertion pass at parameter granularity: the reference inserted
# casts around these ops; here their weights/stats simply never leave
# fp32 masters, and the layers are dtype-preserving — f32 statistics,
# output cast back to the activation dtype). Derived from FP32_OPS.
FP32_PARAM_BLOCKS = frozenset({
    "BatchNorm", "LayerNorm", "InstanceNorm", "GroupNorm",
    "L2Normalization", "LRN",
})
