"""Automatic mixed precision (reference: ``python/mxnet/amp/`` +
``src/nnvm/low_precision_pass.cc`` [unverified]).

Reference design: op allow/deny lists + namespace monkey-patching inserting
casts, dynamic loss scaling with overflow skip. TPU design: bf16 is the
native MXU dtype and needs no loss scaling for typical nets, so
``amp.init()`` sets a bf16 compute policy (consumed by ``TrainStep`` /
``convert_hybrid_block``); fp16 keeps the reference's dynamic loss scaler.
The allow/deny lists survive as data (``amp.lists``) for API parity and for
the cast-insertion pass in ``convert_model``.
"""

from __future__ import annotations

import os as _os

import numpy as _np

import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from . import lists

__all__ = [
    "init",
    "init_trainer",
    "scale_loss",
    "unscale",
    "convert_model",
    "convert_hybrid_block",
    "LossScaler",
    "lists",
    "current_dtype",
    "default_amp",
    "fp32_param_names",
    "reset",
]

_STATE = {"initialized": False, "target_dtype": None}


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Enable mixed precision globally (reference: ``amp.init``).

    ``TrainStep`` built afterwards without an explicit ``amp=`` argument
    adopts this dtype as its compute policy (norm params pinned fp32 per
    ``lists.FP32_PARAM_BLOCKS``; float16 adds the in-graph dynamic loss
    scaler)."""
    if str(target_dtype) not in ("bfloat16", "float16"):
        raise MXNetError("target_dtype must be bfloat16 or float16")
    _STATE["initialized"] = True
    _STATE["target_dtype"] = str(target_dtype)


def reset():
    """Drop the global AMP default (tests / explicit opt-out)."""
    _STATE["initialized"] = False
    _STATE["target_dtype"] = None


def current_dtype():
    return _STATE["target_dtype"] if _STATE["initialized"] else None


def default_amp():
    """The AMP dtype a ``TrainStep(amp=None)`` adopts: ``amp.init()``'s
    global target if set, else ``MXTPU_AMP`` from the environment
    (``bfloat16``/``float16``; ``0``/``off`` or unset -> None)."""
    if _STATE["initialized"]:
        return _STATE["target_dtype"]
    v = _os.environ.get("MXTPU_AMP", "").strip().lower()
    if v in ("", "0", "off", "false", "none"):
        return None
    if v in ("bfloat16", "bf16"):
        return "bfloat16"
    if v in ("float16", "fp16", "half"):
        return "float16"
    raise MXNetError(
        f"MXTPU_AMP={v!r}: expected bfloat16, float16, or 0/off")


def fp32_param_names(net) -> frozenset:
    """Names of ``net``'s parameters pinned to fp32 under AMP — the
    allow/deny cast-insertion pass collapsed to parameter granularity:
    every parameter owned by a norm-family block
    (``lists.FP32_PARAM_BLOCKS``) keeps its fp32 master as the compute
    value; everything else is cast to the compute dtype inside the
    jitted step."""
    names = set()

    def visit(block):
        if type(block).__name__ in lists.FP32_PARAM_BLOCKS:
            for p in block._reg_params.values():
                names.add(p.name)
        for child in getattr(block, "_children", {}).values():
            visit(child)

    visit(net)
    return frozenset(names)


class LossScaler:
    """Dynamic loss scaling (reference: ``amp/loss_scaler.py``).

    Every overflow step is SKIPPED (no optimizer update). The scale:

    - doubles (``scale_factor``) after ``scale_window`` consecutive
      clean steps;
    - halves when overflows are too frequent: more than ``tolerance``
      of the steps since the last scale change overflowed (a lone spike
      long after the last rescale skips without shrinking the scale —
      the documented skip accounting; ``tolerance=0`` restores
      halve-on-every-overflow). Floor 1.0.

    This host-side class drives the eager ``Trainer`` path
    (``amp.init_trainer``). ``TrainStep(amp='float16')`` runs the same
    grow/halve/skip schedule *inside* the jitted step (device-carried
    scale, ``lax.cond``-skipped update) using this class purely as the
    hyperparameter carrier — overflow steps cost no host sync there.
    """

    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000, tolerance=0.05):
        self.loss_scale = float(init_scale)
        self._scale_factor = float(scale_factor)
        self._scale_window = int(scale_window)
        self._tolerance = float(tolerance)
        self._unskipped = 0
        self._iter = 0
        self._last_rescale_iter = -1
        self._overflows_since_rescale = 0
        self._total_skipped = 0

    @property
    def scale_window(self):
        return self._scale_window

    @property
    def scale_factor(self):
        return self._scale_factor

    @property
    def tolerance(self):
        return self._tolerance

    @property
    def total_skipped(self):
        return self._total_skipped

    def has_overflow(self, params) -> bool:
        for p in params:
            g = p._data._grad if p._data is not None else None
            if g is None:
                continue
            if not bool(jnp.isfinite(g.data).all()):
                return True
        return False

    def update_scale(self, overflow: bool):
        self._iter += 1
        if overflow:
            self._total_skipped += 1
            self._unskipped = 0
            self._overflows_since_rescale += 1
            since = self._iter - self._last_rescale_iter
            if self._overflows_since_rescale / float(since) > self._tolerance:
                self.loss_scale = max(
                    self.loss_scale / self._scale_factor, 1.0)
                self._last_rescale_iter = self._iter
                self._overflows_since_rescale = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0
                self._last_rescale_iter = self._iter
                self._overflows_since_rescale = 0

    def stats(self) -> dict:
        return {
            "loss_scale": self.loss_scale,
            "steps": self._iter,
            "skipped": self._total_skipped,
            "unskipped_streak": self._unskipped,
        }


def init_trainer(trainer):
    """Attach a dynamic loss scaler to a Trainer (reference API).

    ``trainer.step`` afterwards: grads are unscaled via rescale_grad; steps
    with non-finite grads are skipped and the scale lowered."""
    if not _STATE["initialized"]:
        raise MXNetError("call amp.init() before amp.init_trainer()")
    if _STATE["target_dtype"] == "bfloat16":
        # bf16 has fp32's exponent range: no scaling needed; keep a scaler
        # with scale 1 so scale_loss stays a no-op passthrough
        trainer._amp_loss_scaler = LossScaler(init_scale=1.0)
        trainer._amp_original_scale = trainer._scale
        return
    scaler = LossScaler()
    trainer._amp_loss_scaler = scaler
    trainer._amp_original_scale = trainer._scale
    _patch_trainer_step(trainer)


def _patch_trainer_step(trainer):
    trainer._amp_unscaled = False

    def step(batch_size, ignore_stale_grad=False):
        if not trainer._kv_initialized:
            trainer._init_kvstore()
        scaler = trainer._amp_loss_scaler
        overflow = scaler.has_overflow(trainer._params)
        if not overflow:
            # unscale folded into rescale_grad — unless amp.unscale() was
            # called manually after backward (for grad clipping), in which
            # case grads already carry 1/scale
            scale = 1.0 if trainer._amp_unscaled else scaler.loss_scale
            trainer._optimizer.rescale_grad = (
                trainer._amp_original_scale / batch_size / scale
            )
            trainer._allreduce_grads()
            trainer._update(ignore_stale_grad)
        trainer._amp_unscaled = False
        scaler.update_scale(overflow)

    trainer.step = step


class scale_loss:
    """``with amp.scale_loss(loss, trainer) as scaled: scaled.backward()``"""

    def __init__(self, loss, trainer):
        self._trainer = trainer
        scaler = getattr(trainer, "_amp_loss_scaler", None)
        scale = scaler.loss_scale if scaler is not None else 1.0
        if isinstance(loss, (list, tuple)):
            self._scaled = [l * scale for l in loss]
        else:
            self._scaled = loss * scale

    def __enter__(self):
        return self._scaled

    def __exit__(self, *exc):
        return False


def unscale(trainer):
    """Divide current grads by the loss scale (for manual clipping between
    backward and step); the next step() skips its own unscale fold."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        return
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        if p._data is not None and p._data._grad is not None:
            g = p._data._grad
            g._rebind(g.data * inv)
    trainer._amp_unscaled = True


def _target_jnp_dtype():
    return jnp.bfloat16 if _STATE["target_dtype"] == "bfloat16" else jnp.float16


def convert_model(net, target_dtype=None):
    """Cast a model's parameters to the AMP dtype, keeping norm-layer params
    and stats in fp32 (the allow/deny-list pass of the reference collapses
    to this under XLA, which fuses the casts)."""
    return convert_hybrid_block(net, target_dtype)


def convert_hybrid_block(net, target_dtype=None):
    dt = target_dtype or _STATE["target_dtype"] or "bfloat16"
    net.cast(dt)  # BatchNorm.cast keeps its params fp32 (see basic_layers)
    return net
