"""Automatic mixed precision (reference: ``python/mxnet/amp/`` +
``src/nnvm/low_precision_pass.cc`` [unverified]).

Reference design: op allow/deny lists + namespace monkey-patching inserting
casts, dynamic loss scaling with overflow skip. TPU design: bf16 is the
native MXU dtype and needs no loss scaling for typical nets, so
``amp.init()`` sets a bf16 compute policy (consumed by ``TrainStep`` /
``convert_hybrid_block``); fp16 keeps the reference's dynamic loss scaler.
The allow/deny lists survive as data (``amp.lists``) for API parity and for
the cast-insertion pass in ``convert_model``.
"""

from __future__ import annotations

import numpy as _np

import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from . import lists

__all__ = [
    "init",
    "init_trainer",
    "scale_loss",
    "unscale",
    "convert_model",
    "convert_hybrid_block",
    "LossScaler",
    "lists",
]

_STATE = {"initialized": False, "target_dtype": None}


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Enable mixed precision globally (reference: ``amp.init``)."""
    if str(target_dtype) not in ("bfloat16", "float16"):
        raise MXNetError("target_dtype must be bfloat16 or float16")
    _STATE["initialized"] = True
    _STATE["target_dtype"] = str(target_dtype)


def current_dtype():
    return _STATE["target_dtype"] if _STATE["initialized"] else None


class LossScaler:
    """Dynamic loss scaling (reference: ``amp/loss_scaler.py``): double every
    ``scale_window`` clean steps, halve on overflow, skip the step."""

    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000, tolerance=0.05):
        self.loss_scale = init_scale
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._unskipped = 0

    def has_overflow(self, params) -> bool:
        for p in params:
            g = p._data._grad if p._data is not None else None
            if g is None:
                continue
            if not bool(jnp.isfinite(g.data).all()):
                return True
        return False

    def update_scale(self, overflow: bool):
        if overflow:
            self.loss_scale = max(self.loss_scale / self._scale_factor, 1.0)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0


def init_trainer(trainer):
    """Attach a dynamic loss scaler to a Trainer (reference API).

    ``trainer.step`` afterwards: grads are unscaled via rescale_grad; steps
    with non-finite grads are skipped and the scale lowered."""
    if not _STATE["initialized"]:
        raise MXNetError("call amp.init() before amp.init_trainer()")
    if _STATE["target_dtype"] == "bfloat16":
        # bf16 has fp32's exponent range: no scaling needed; keep a scaler
        # with scale 1 so scale_loss stays a no-op passthrough
        trainer._amp_loss_scaler = LossScaler(init_scale=1.0)
        trainer._amp_original_scale = trainer._scale
        return
    scaler = LossScaler()
    trainer._amp_loss_scaler = scaler
    trainer._amp_original_scale = trainer._scale
    _patch_trainer_step(trainer)


def _patch_trainer_step(trainer):
    trainer._amp_unscaled = False

    def step(batch_size, ignore_stale_grad=False):
        if not trainer._kv_initialized:
            trainer._init_kvstore()
        scaler = trainer._amp_loss_scaler
        overflow = scaler.has_overflow(trainer._params)
        if not overflow:
            # unscale folded into rescale_grad — unless amp.unscale() was
            # called manually after backward (for grad clipping), in which
            # case grads already carry 1/scale
            scale = 1.0 if trainer._amp_unscaled else scaler.loss_scale
            trainer._optimizer.rescale_grad = (
                trainer._amp_original_scale / batch_size / scale
            )
            trainer._allreduce_grads()
            trainer._update(ignore_stale_grad)
        trainer._amp_unscaled = False
        scaler.update_scale(overflow)

    trainer.step = step


class scale_loss:
    """``with amp.scale_loss(loss, trainer) as scaled: scaled.backward()``"""

    def __init__(self, loss, trainer):
        self._trainer = trainer
        scaler = getattr(trainer, "_amp_loss_scaler", None)
        scale = scaler.loss_scale if scaler is not None else 1.0
        if isinstance(loss, (list, tuple)):
            self._scaled = [l * scale for l in loss]
        else:
            self._scaled = loss * scale

    def __enter__(self):
        return self._scaled

    def __exit__(self, *exc):
        return False


def unscale(trainer):
    """Divide current grads by the loss scale (for manual clipping between
    backward and step); the next step() skips its own unscale fold."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        return
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        if p._data is not None and p._data._grad is not None:
            g = p._data._grad
            g._rebind(g.data * inv)
    trainer._amp_unscaled = True


def _target_jnp_dtype():
    return jnp.bfloat16 if _STATE["target_dtype"] == "bfloat16" else jnp.float16


def convert_model(net, target_dtype=None):
    """Cast a model's parameters to the AMP dtype, keeping norm-layer params
    and stats in fp32 (the allow/deny-list pass of the reference collapses
    to this under XLA, which fuses the casts)."""
    return convert_hybrid_block(net, target_dtype)


def convert_hybrid_block(net, target_dtype=None):
    dt = target_dtype or _STATE["target_dtype"] or "bfloat16"
    net.cast(dt)  # BatchNorm.cast keeps its params fp32 (see basic_layers)
    return net
