"""Device contexts: ``mx.cpu()``, ``mx.gpu()``, ``mx.tpu()``.

TPU-native analogue of the reference's ``python/mxnet/context.py`` and the
C++ ``Context`` struct in ``include/mxnet/base.h`` [unverified]. A Context
names a logical device ``(device_type, device_id)`` and resolves to a concrete
``jax.Device``. The north-star adds ``mx.tpu()`` as the accelerator context;
``mx.gpu()`` is kept as a migration alias that resolves to the platform's
accelerator so reference-era scripts run unchanged.

A thread-local default-context stack supports ``with mx.tpu(0):`` scoping,
mirroring the reference's ``Context.default_ctx`` behavior.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax

from .base import MXNetError

__all__ = [
    "Context",
    "cpu",
    "cpu_pinned",
    "gpu",
    "tpu",
    "current_context",
    "num_gpus",
    "num_tpus",
    "num_devices",
]

_ACCEL_PLATFORMS = ("tpu", "gpu", "cuda", "rocm", "axon")


class Context:
    """A logical device. ``device_type`` in {'cpu', 'gpu', 'tpu', 'cpu_pinned'}.

    ``gpu`` and ``tpu`` both resolve to the platform accelerator (TPU on TPU
    machines); ``cpu_pinned`` is an alias of cpu (host memory is unified from
    XLA's point of view).
    """

    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 4: "tpu"}
    devstr2type = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "tpu": 4}

    _default_ctx = threading.local()

    def __init__(self, device_type: str, device_id: int = 0):
        if isinstance(device_type, Context):
            device_id = device_type.device_id
            device_type = device_type.device_type
        if device_type not in self.devstr2type:
            raise MXNetError(f"unknown device type {device_type!r}")
        self.device_type = device_type
        self.device_id = int(device_id)

    # -- resolution to concrete jax devices ---------------------------------
    def jax_device(self) -> jax.Device:
        """Resolve to a concrete ``jax.Device`` (raises if absent)."""
        devs = self._platform_devices(self.device_type)
        if not devs:
            raise MXNetError(
                f"no devices available for context {self}; "
                f"jax backend has {[d.platform for d in jax.devices()]}"
            )
        if self.device_id >= len(devs):
            raise MXNetError(f"{self}: only {len(devs)} such device(s) present")
        return devs[self.device_id]

    @staticmethod
    def _platform_devices(device_type: str):
        all_devs = jax.devices()
        if device_type in ("cpu", "cpu_pinned"):
            cpus = [d for d in all_devs if d.platform == "cpu"]
            if cpus:
                return cpus
            try:
                return jax.devices("cpu")
            except RuntimeError:
                return all_devs  # single-backend runtime: one device namespace
        # gpu/tpu: any accelerator platform
        accels = [d for d in all_devs if d.platform in _ACCEL_PLATFORMS]
        return accels or all_devs

    # -- identity -----------------------------------------------------------
    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    __str__ = __repr__

    # -- default-context stack ---------------------------------------------
    def __enter__(self):
        if not hasattr(Context._default_ctx, "stack"):
            Context._default_ctx.stack = []
        Context._default_ctx.stack.append(self)
        return self

    def __exit__(self, *exc):
        Context._default_ctx.stack.pop()
        return False

    def empty_cache(self):
        """Reference freed the GPU memory pool here; XLA manages HBM itself."""

    @classmethod
    def default_ctx(cls) -> "Context":
        stack = getattr(cls._default_ctx, "stack", None)
        if stack:
            return stack[-1]
        return _initial_default_context()


def _initial_default_context() -> Context:
    """Accelerator if present, else cpu (reference defaulted to cpu(0))."""
    global _CACHED_INITIAL
    if _CACHED_INITIAL is None:
        accels = [d for d in jax.devices() if d.platform in _ACCEL_PLATFORMS]
        _CACHED_INITIAL = Context("tpu", 0) if accels else Context("cpu", 0)
    return _CACHED_INITIAL


_CACHED_INITIAL: Optional[Context] = None


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def gpu(device_id: int = 0) -> Context:
    """Migration alias: resolves to the platform accelerator (TPU here)."""
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def current_context() -> Context:
    return Context.default_ctx()


def num_devices(device_type: str = "tpu") -> int:
    return len(Context._platform_devices(device_type))


def num_gpus() -> int:
    devs = [d for d in jax.devices() if d.platform in _ACCEL_PLATFORMS]
    return len(devs)


def num_tpus() -> int:
    return num_gpus()
