"""Numeric-debug modes (SURVEY aux subsystems: race detection / debug).

The reference's debugging levers were the naive (synchronous) engine mode
and NaN checks inside ops; the TPU-native equivalents:

- ``set_nan_check(True)``: flip ``jax_debug_nans`` — XLA re-runs any
  computation producing a NaN un-jitted and raises at the exact primitive
  (stronger than the reference's per-op output scan).
- ``nan_guard()``: context-manager form for one training section.
- ``check_nan(arr)``: explicit assertion on an NDArray/array (the
  reference's ``MXNET_NAN_CHECK``-style spot check).
- synchronous execution: ``MXNET_ENGINE_TYPE=NaiveEngine`` (see
  ``mxnet_tpu.engine``) — kept there, referenced here for discoverability.
"""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp
import numpy as _np

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["set_nan_check", "nan_guard", "check_nan"]


def set_nan_check(enabled: bool):
    """Enable/disable global NaN detection (jax_debug_nans)."""
    jax.config.update("jax_debug_nans", bool(enabled))


@contextlib.contextmanager
def nan_guard():
    """Scope with NaN detection active."""
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", True)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


def check_nan(arr, name="array"):
    """Raise MXNetError if ``arr`` contains NaN/Inf (host sync point)."""
    a = arr.asnumpy() if isinstance(arr, NDArray) else _np.asarray(
        jax.device_get(arr)
    )
    if not _np.isfinite(a).all():
        n_nan = int(_np.isnan(a).sum())
        n_inf = int(_np.isinf(a).sum())
        raise MXNetError(
            f"{name} contains {n_nan} NaN and {n_inf} Inf values "
            f"(shape {a.shape})"
        )
    return arr
