"""KVStore facade (reference: ``python/mxnet/kvstore/`` over
``src/kvstore/`` + ps-lite [unverified]).

TPU-native design (SURVEY.md §2.3/§5): none of the reference's transports
(device p2p copies, NCCL, ZMQ parameter server) is rebuilt. Gradient
synchronization is an XLA collective compiled into the step program
(``psum`` over the mesh ``data`` axis, riding ICI). The KVStore classes
survive as the same Python API so Trainer-level code ports unchanged:

- 'local' / 'device' / 'nccl': in-process store; push accumulates the
  device-replica list (a no-op sum when GSPMD already all-reduced), pull
  broadcasts.
- 'dist_sync' / 'dist_async' / 'horovod' / 'byteps': multi-host data
  parallelism over the jax distributed runtime (one process per host); push
  triggers a cross-host psum via ``mxnet_tpu.parallel``.
"""

from .kvstore import KVStore, KVStoreBase, create
from . import kvstore_server  # noqa: F401

__all__ = ["KVStore", "KVStoreBase", "create"]
