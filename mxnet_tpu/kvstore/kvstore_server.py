"""KVStore 'server' role (reference: ``src/kvstore/kvstore_dist_server.h``
[unverified]: servers applied the optimizer to pushed grads).

On TPU there are no server processes — every host runs the same SPMD program
and the optimizer runs data-parallel on all of them. This module keeps the
reference's entry point so launch scripts with a server role degrade
gracefully: a 'server' process simply joins the coordinator and idles (the
launcher should allocate 0 servers)."""

from __future__ import annotations

import os


def run_server():  # pragma: no cover - exercised via tools/launch.py
    role = os.environ.get("MXNET_TPU_ROLE", "worker")
    if role == "server":
        raise SystemExit(
            "mxnet_tpu has no parameter-server role: gradient sync is an XLA "
            "collective inside the step program. Launch with 0 servers."
        )
