"""Gradient compression (reference: ``src/kvstore/gradient_compression.cc``
[unverified]).

The reference's 2-bit scheme quantizes each worker's gradient to
{-threshold, 0, +threshold} with error-feedback residual accumulation,
packing 16 values per uint32 on the wire. The TPU build keeps the exact
quantization + residual semantics (they change optimization dynamics and
must match) and implements the packed wire format as pure jax ops — there
is no ZMQ wire here, but push() round-trips through pack/unpack so the
on-device representation is the compressed one (4 values/byte), which is
also what a future DCN transport would send.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import MXNetError

__all__ = ["GradientCompression", "quantize_2bit", "dequantize_2bit",
           "pack_2bit", "unpack_2bit"]


def quantize_2bit(grad_plus_residual, threshold):
    """-> (quantized {-t,0,+t}, new_residual). Error feedback: the residual
    carries everything the quantizer dropped into the next step."""
    t = jnp.asarray(threshold, grad_plus_residual.dtype)
    q = jnp.where(
        grad_plus_residual >= t, t,
        jnp.where(grad_plus_residual <= -t, -t,
                  jnp.zeros_like(grad_plus_residual)),
    )
    return q, grad_plus_residual - q


def dequantize_2bit(q, threshold):  # identity in value space; parity hook
    return q


def pack_2bit(q, threshold):
    """Encode {-t,0,+t} into 2-bit codes, 4 per uint8 (wire format).

    Codes: 0 -> 0, +t -> 1, -t -> 2. Returns (packed uint8[ceil(n/4)],
    original size)."""
    flat = q.reshape(-1)
    t = jnp.asarray(threshold, flat.dtype)
    codes = jnp.where(flat >= t, 1, jnp.where(flat <= -t, 2, 0)).astype(
        jnp.uint8
    )
    n = codes.shape[0]
    pad = (-n) % 4
    codes = jnp.pad(codes, (0, pad))
    codes = codes.reshape(-1, 4)
    shifts = jnp.arange(4, dtype=jnp.uint8) * 2
    packed = jnp.sum(codes << shifts, axis=1).astype(jnp.uint8)
    return packed, n


def unpack_2bit(packed, n, threshold, dtype=jnp.float32):
    codes = (packed[:, None] >> (jnp.arange(4, dtype=jnp.uint8) * 2)) & 0x3
    codes = codes.reshape(-1)[:n]
    t = jnp.asarray(threshold, dtype)
    return jnp.where(codes == 1, t, jnp.where(codes == 2, -t,
                                              jnp.zeros((), dtype)))


class GradientCompression:
    """Per-key error-feedback compressor held by a KVStore."""

    def __init__(self, params):
        params = dict(params)
        ctype = params.get("type", params.get("compression", "2bit"))
        if ctype != "2bit":
            raise MXNetError(
                f"unsupported gradient compression type {ctype!r} "
                "(reference supports 2bit)"
            )
        self.type = ctype
        self.threshold = float(params.get("threshold", 0.5))
        self._residuals = {}

    def compress(self, key, grad):
        """grad (jax array) -> dequantized compressed gradient; updates the
        residual for ``key``. Shapes are static per key."""
        r = self._residuals.get(key)
        if r is None or r.shape != grad.shape:
            r = jnp.zeros_like(grad)
        q, new_r = quantize_2bit(grad + r.astype(grad.dtype), self.threshold)
        self._residuals[key] = new_r
        # round-trip the wire format so the compressed representation is
        # what actually flows (and pack/unpack stay correct)
        packed, n = pack_2bit(q, self.threshold)
        out = unpack_2bit(packed, n, self.threshold, q.dtype)
        return out.reshape(grad.shape)
