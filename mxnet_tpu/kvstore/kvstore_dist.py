"""Distributed KVStore over the jax coordination service (reference:
``src/kvstore/kvstore_dist.h`` + ``3rdparty/ps-lite`` [unverified]).

Architecture swap (SURVEY.md §5): the reference ran a ZMQ parameter server
(scheduler + S servers + W workers, server-side optimizer). Here the only
hand-written distributed piece is rendezvous: `jax.distributed.initialize`
(coordinator = ps-lite scheduler analogue) forms one global device mesh, and
gradient sync is an XLA `psum` over the mesh's 'data' axis — compiled into
the step, riding ICI/DCN. Push/pull therefore degenerate to the local path
plus a cross-process all-reduce for eager (non-jitted) callers.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .. import telemetry as _tel
from .kvstore import KVStore, KVStoreBase

__all__ = ["KVStoreDist"]


@KVStoreBase.register
class KVStoreDist(KVStore):
    """Multi-host data-parallel store."""

    def __init__(self, kv_type="dist_sync"):
        super().__init__(kv_type)
        self._rank = 0
        self._num_workers = 1
        self._initialized_dist = False
        # dist_async: bounded-staleness mode (round-4 verdict item 8).
        # The reference's async let each worker hit the parameter server
        # without waiting; with collectives as the only transport, the
        # TPU-native analogue is LOCAL apply (push returns without any
        # cross-host wait) plus a parameter-averaging collective every
        # `staleness_bound` pushes per key — local SGD / periodic
        # averaging, which bounds divergence exactly the way the
        # reference's staleness bound did. Requires updater-on-store
        # (like the reference's server-side updater) and the SPMD
        # contract that workers push each key at the same cadence (the
        # reconcile is a collective; mismatched cadence hangs like any
        # mismatched collective).
        self._async = kv_type == "dist_async"
        self._push_counts: dict = {}
        self._warned_compress = False
        from ..base import env_int

        self._staleness_bound = max(1, env_int(
            "MXTPU_ASYNC_STALENESS_BOUND", 8))
        self._maybe_init_dist()

    def _maybe_init_dist(self):
        """Join the coordinator if launch env vars are present (set by
        ``tools/launch.py``; reference used DMLC_PS_ROOT_URI/DMLC_ROLE)."""
        coord = os.environ.get("MXNET_TPU_COORDINATOR")
        nproc = os.environ.get("MXNET_TPU_NUM_PROCS")
        pid = os.environ.get("MXNET_TPU_PROC_ID")
        if coord and nproc and pid and not self._initialized_dist:
            from ..parallel import init_process_group

            init_process_group(
                coordinator_address=coord,
                num_processes=int(nproc),
                process_id=int(pid),
            )
            self._initialized_dist = True
        self._rank = jax.process_index()
        self._num_workers = jax.process_count()

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    def _warn_if_mesh_owns_sync(self):
        """One-time redundancy alarm: when the process-global mesh spans
        every worker, gradient sync already happens IN-GRAPH (GSPMD psum
        inside the jitted step) — an eager host push on top of it
        double-sums. ``Trainer._allreduce_grads`` skips automatically;
        direct kvstore users get this warning once."""
        if getattr(self, "_warned_mesh_sync", False):
            return
        from ..parallel import sharding as _shard

        if _shard.mesh_spans_processes():
            self._warned_mesh_sync = True
            import warnings

            warnings.warn(
                "KVStore.push with a process-global mesh spanning all "
                "workers: gradient sync is in-graph (mesh psum); the "
                "host allreduce is redundant and double-sums if the "
                "grads were already synced. Build the step on the mesh "
                "and drop the push/pull loop.", RuntimeWarning,
                stacklevel=3)

    def _push_impl(self, key, value, priority=0):
        self._warn_if_mesh_owns_sync()
        keys = _l(key)
        for k, vals in zip(keys, self._grouped(keys, value)):
            k = str(k)
            if k not in self._data:
                raise MXNetError(f"key {k} not initialized in kvstore")
            datas = [v.data for v in vals]
            if self._async and self._updater is not None \
                    and self._num_workers > 1:
                self._push_async(k, datas)
                continue
            # reference worker order (``kvstore_dist.h`` [unverified]):
            # aggregate the local device replicas FIRST, then compress
            # once per worker, then ship — so the wire carries one
            # compressed gradient per worker
            agg = datas[0]
            for v in datas[1:]:
                agg = agg + v
            if self._compression is not None and self._num_workers > 1:
                agg = self._cross_host_sum_compressed(k, agg)
            else:
                if self._compression is not None:
                    agg = self._compression.compress((k, "w"), agg)
                agg = self._cross_host_sum(agg)
            if self._updater is not None:
                self._updater(int(k) if k.isdigit() else k, NDArray(agg),
                              self._data[k])
            else:
                self._data[k]._rebind(agg)

    def _push_async(self, k, datas):
        """Bounded-staleness push: apply the LOCAL gradient immediately
        (no cross-host wait — the worker runs ahead on its own replica,
        reads are allowed to be stale), then every ``staleness_bound``
        pushes reconcile the replicas with one parameter-averaging
        collective. Ref: dist_async server-side updater + staleness
        bound (``src/kvstore/kvstore_dist_server.h`` [unverified])."""
        agg = datas[0]
        for v in datas[1:]:
            agg = agg + v
        if self._compression is not None and not self._warned_compress:
            # the local apply transmits nothing, so quantizing it would
            # add error while saving zero wire bytes; the reconcile ships
            # full weights (averaging quantized weights is not the
            # gradient-compression contract). Signal instead of silently
            # degrading.
            self._warned_compress = True
            import warnings

            warnings.warn(
                "gradient compression has no wire transfer to compress "
                "under dist_async local-apply; ignored (the periodic "
                "reconcile ships full-precision parameters)",
                RuntimeWarning, stacklevel=3)
        self._updater(int(k) if k.isdigit() else k, NDArray(agg),
                      self._data[k])
        c = self._push_counts.get(k, 0) + 1
        self._push_counts[k] = c
        if c % self._staleness_bound == 0:
            w = self._data[k].data
            avg = self._cross_host_sum(w) / self._num_workers
            self._data[k]._rebind(avg)

    def _cross_host_sum_compressed(self, k, agg):
        """Real wire-byte 2-bit transfer: quantize + error-feedback on the
        worker-local aggregate, all-gather the PACKED uint8 codes (16x
        fewer wire bytes than f32), dequantize + sum after transfer
        (reference: server-side dequantize in ``DataHandleEx``)."""
        from jax.experimental import multihost_utils

        from .compression import pack_2bit, quantize_2bit, unpack_2bit

        comp = self._compression
        rkey = (k, "w")
        r = comp._residuals.get(rkey)
        if r is None or r.shape != agg.shape:
            r = jnp.zeros_like(agg)
        q, new_r = quantize_2bit(agg + r.astype(agg.dtype), comp.threshold)
        comp._residuals[rkey] = new_r
        packed, n = pack_2bit(q, comp.threshold)
        gathered = multihost_utils.process_allgather(packed)  # (W, bytes)
        # bookkeeping for tests/telemetry: logical wire bytes this push
        self.last_push_wire_bytes = int(gathered.shape[-1])
        if _tel._ENABLED:
            _tel.registry().counter("kvstore/allreduce_wire_bytes").inc(
                self.last_push_wire_bytes)
        total = None
        for w in range(gathered.shape[0]):
            dq = unpack_2bit(gathered[w], n, comp.threshold, agg.dtype)
            total = dq if total is None else total + dq
        return total.reshape(agg.shape)

    def _cross_host_sum(self, arr):
        if self._num_workers == 1:
            return arr
        # eager cross-process psum over all global devices: each process
        # contributes its replica; result is identical on every host
        from ..parallel import all_reduce_eager

        if not _tel._ENABLED:
            return all_reduce_eager(arr)
        import time as _time

        t0 = _time.perf_counter()
        with _tel.span("kvstore.allreduce",
                       {"bytes": int(getattr(arr, "nbytes", 0) or 0)}):
            out = all_reduce_eager(arr)
        reg = _tel.registry()
        reg.histogram("kvstore/allreduce_time_s").observe(
            _time.perf_counter() - t0)
        reg.counter("kvstore/allreduce_bytes").inc(
            int(getattr(arr, "nbytes", 0) or 0))
        return out

    def barrier(self):
        super().barrier()
        if self._num_workers > 1:
            # dummy collective as a barrier
            self._cross_host_sum(jnp.zeros(()))


def _l(x):
    return x if isinstance(x, (list, tuple)) else [x]
