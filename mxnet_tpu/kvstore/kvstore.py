"""In-process KVStore implementations (reference: ``src/kvstore/kvstore_local.cc``,
``comm.h``/``comm_tree.h``/``kvstore_nccl.h`` [unverified]).

The reference's three intra-node reduce strategies (CPU reduce, device tree
reduce, NCCL ring) all collapse to one thing on TPU: XLA emits the optimal
ICI collective for a mesh-sharded array, so ``push`` here is a plain sum over
the replica list (length 1 when GSPMD already holds the globally-reduced
gradient). Multi-host ('dist_*') layers a cross-process psum from
``mxnet_tpu.parallel`` on top.
"""

from __future__ import annotations

import pickle
from typing import Dict, List, Optional

import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .. import optimizer as opt
from .. import telemetry as _tel

__all__ = ["KVStoreBase", "KVStore", "create"]


def _nbytes(values) -> int:
    """Logical payload bytes of a push/pull value tree (telemetry only —
    called exclusively on the enabled path)."""
    total = 0
    stack = [values]
    while stack:
        v = stack.pop()
        if isinstance(v, (list, tuple)):
            stack.extend(v)
        else:
            data = getattr(v, "data", v)
            total += int(getattr(data, "nbytes", 0) or 0)
    return total


class KVStoreBase:
    """Pluggable backend registry (reference: 2.0-era ``KVStoreBase``)."""

    kv_registry: Dict[str, type] = {}

    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        KVStoreBase.kv_registry[name] = klass
        return klass

    # capability names
    OPTIMIZER = "optimizer"

    @staticmethod
    def is_capable(capability):  # pragma: no cover - overridden
        raise NotImplementedError


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


@KVStoreBase.register
class KVStore(KVStoreBase):
    """Single-process store ('local' / 'device' / 'nccl' types)."""

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._data: Dict = {}
        self._updater: Optional[opt.Updater] = None
        self._update_on_kvstore = False
        self._compression = None

    # --------------------------------------------------------------- info
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    @staticmethod
    def is_capable(capability):
        return capability == KVStoreBase.OPTIMIZER

    # ---------------------------------------------------------------- API
    def init(self, key, value):
        if _tel._ENABLED:
            with _tel.span("kvstore.init"):
                self._init_impl(key, value)
        else:
            self._init_impl(key, value)

    def _init_impl(self, key, value):
        keys, values = _as_list(key), _as_list(value)
        for k, v in zip(keys, values):
            k = str(k)
            if k in self._data:
                continue
            self._data[k] = NDArray(jnp.array(v.data))

    def push(self, key, value, priority=0):
        """Telemetry seam: span + bytes/latency metrics around the
        subclass-specific ``_push_impl`` (dist overrides the impl, not
        the wrapper, so both stores share the instrumentation)."""
        if not _tel._ENABLED:
            self._push_impl(key, value, priority)
            return
        import time as _time

        t0 = _time.perf_counter()
        with _tel.span("kvstore.push", {"type": self._type}):
            self._push_impl(key, value, priority)
        reg = _tel.registry()
        reg.histogram("kvstore/push_time_s").observe(
            _time.perf_counter() - t0)
        reg.counter("kvstore/push_bytes").inc(_nbytes(value))

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        if not _tel._ENABLED:
            self._pull_impl(key, out, priority, ignore_sparse)
            return
        with _tel.span("kvstore.pull", {"type": self._type}):
            self._pull_impl(key, out, priority, ignore_sparse)

    def _push_impl(self, key, value, priority=0):
        keys = _as_list(key)
        for k, vals in zip(keys, self._grouped(keys, value)):
            k = str(k)
            if k not in self._data:
                raise MXNetError(f"key {k} not initialized in kvstore")
            # per-replica compression before the reduce (reference: each
            # worker compresses its own gradient; residual is per worker)
            datas = [v.data for v in vals]
            if self._compression is not None:
                datas = [
                    self._compression.compress((k, i), d)
                    for i, d in enumerate(datas)
                ]
            # reduce over device replicas (reference: Comm::Reduce / NCCL)
            agg = datas[0]
            for v in datas[1:]:
                agg = agg + v
            if self._updater is not None:
                self._updater(int(k) if k.isdigit() else k, NDArray(agg),
                              self._data[k])
            else:
                self._data[k]._rebind(agg)

    def _pull_impl(self, key, out=None, priority=0, ignore_sparse=True):
        keys = _as_list(key)
        outs = self._grouped(keys, out)
        for k, dsts in zip(keys, outs):
            k = str(k)
            if k not in self._data:
                raise MXNetError(f"key {k} not initialized in kvstore")
            src = self._data[k]
            for d in dsts:
                d._rebind(src.data)

    def pushpull(self, key, value, out=None, priority=0):
        self.push(key, value, priority)
        self.pull(key, out if out is not None else value, priority)

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows of ``key`` (reference: the
        row_sparse pull the sparse-embedding training loop used to fetch
        live rows of a big table, ``src/kvstore/`` [unverified]).

        ``out`` becomes a PAIR-backed RowSparseNDArray holding exactly the
        requested rows — a gather, never the dense table."""
        from ..ndarray.sparse import RowSparseNDArray

        if row_ids is None:
            raise MXNetError("row_sparse_pull requires row_ids")
        keys = _as_list(key)
        outs = self._grouped(keys, out)
        rids = _as_list(row_ids)
        if len(rids) == 1 and len(keys) > 1:
            rids = rids * len(keys)
        for k, dsts, rid in zip(keys, outs, rids):
            k = str(k)
            if k not in self._data:
                raise MXNetError(f"key {k} not initialized in kvstore")
            src = self._data[k]
            rows = rid.data.astype(jnp.int32).reshape(-1) \
                if isinstance(rid, NDArray) else jnp.asarray(rid, jnp.int32)
            # pull is a READ with set semantics: duplicate requested ids
            # must not double rows when the pair densifies (densify sums)
            import numpy as _nphost
            rows = jnp.asarray(_nphost.unique(_nphost.asarray(rows)),
                               jnp.int32)
            vals = jnp.take(src.data, rows, axis=0)
            for d in dsts:
                if isinstance(d, RowSparseNDArray):
                    d._rs_rows = rows
                    d._rs_vals = vals
                    d._rs_shape = tuple(src.shape)
                    d._rs_dense = None
                else:
                    d._rebind(
                        jnp.zeros(src.shape, src.data.dtype)
                        .at[rows].set(vals)
                    )

    def set_gradient_compression(self, compression_params):
        from .compression import GradientCompression

        self._compression = GradientCompression(compression_params)

    # ----------------------------------------------------- server optimizer
    def set_optimizer(self, optimizer):
        # reference pickles the optimizer to PS servers; here the "server"
        # is in-process
        self._updater = opt.get_updater(optimizer)
        self._update_on_kvstore = True

    @property
    def updater(self):
        return self._updater

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    # ------------------------------------------------------------- helpers
    def _grouped(self, keys, values) -> List[List[NDArray]]:
        values = _as_list(values)
        if len(keys) == 1:
            if values and isinstance(values[0], (list, tuple)):
                return [list(values[0])]
            return [list(values)]
        out = []
        for v in values:
            out.append(list(v) if isinstance(v, (list, tuple)) else [v])
        return out

    def barrier(self):
        from ..engine import wait_for_all

        wait_for_all()

    def _send_command_to_servers(self, head, body):
        pass


_ASYNC_WARNED = [False]


def create(name="local") -> KVStore:
    """Create a KVStore (reference: ``mx.kv.create``).

    'local'/'device'/'nccl' → in-process store (GSPMD handles intra-host
    reduction). 'dist_sync'/'dist_async' → distributed store over the jax
    coordinator (requires `mxnet_tpu.parallel.init_process_group`).

    SEMANTICS NOTE: 'dist_async' implements BOUNDED-STALENESS semantics
    (round-5): with an updater set (``kv.set_optimizer``, the analogue
    of the reference's server-side updater) each push applies LOCALLY
    with no cross-host wait — reads may be stale — and every
    ``MXTPU_ASYNC_STALENESS_BOUND`` pushes (default 8) the replicas
    reconcile with one parameter-averaging collective. This is local
    SGD / periodic averaging: the collectives-native analogue of the
    reference's parameter-server async, with the staleness bound the
    server's consistency knob provided. Workers must push each key at
    the same cadence (the reconcile is a collective). Without an
    updater it degrades to dist_sync semantics.
    """
    if not isinstance(name, str):
        raise MXNetError("name must be a string")
    kind = name.lower()
    if kind in ("local", "device", "nccl", "local_allreduce_cpu",
                "local_allreduce_device"):
        return KVStore(kind)
    if kind in ("dist_sync", "dist_async", "dist_device_sync", "dist",
                "horovod", "byteps"):
        from .kvstore_dist import KVStoreDist

        if kind == "dist_async" and not _ASYNC_WARNED[0]:
            # runtime signal, not just a docstring (advisor round 3):
            # ported scripts get a DIFFERENT async than the reference's
            import warnings

            warnings.warn(
                "kv.create('dist_async') runs bounded-staleness local "
                "apply + periodic parameter averaging (every "
                "MXTPU_ASYNC_STALENESS_BOUND=8 pushes per key), not a "
                "parameter-server async: pushes return without cross-host "
                "waits and pulls may read stale replicas, reconciled at "
                "the bound. Requires kv.set_optimizer; workers must push "
                "each key at the same cadence.",
                RuntimeWarning,
                stacklevel=2,
            )
            _ASYNC_WARNED[0] = True
        return KVStoreDist(kind)
    raise MXNetError(f"unknown KVStore type {name!r}")
