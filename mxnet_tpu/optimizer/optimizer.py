"""Optimizer classes (reference: ``python/mxnet/optimizer/optimizer.py``
[unverified]; fused update kernels ``src/operator/optimizer_op.cc``).

Design: every optimizer's math lives in a pure fused-update op
(``ops/optimizer_op.py``). The per-param ``update()`` path runs that op
through a cached ``jax.jit`` wrapper in which the *varying* hypers (lr, wd,
bias-correction-adjusted lr) are dynamic scalar operands — so changing the
learning rate never retraces — while structural hypers (momentum, betas) are
compile-time constants. ``Trainer`` additionally offers a fully fused
whole-model step (one XLA executable for all params, donated buffers): the
TPU analogue of the reference's multi-tensor ``multi_sgd_update`` kernels.
"""

from __future__ import annotations

import functools
import logging
import math
import pickle
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..ops import optimizer_op as _fused
from .lr_scheduler import LRScheduler

__all__ = [
    "Optimizer",
    "SGD",
    "Signum",
    "NAG",
    "Adam",
    "AdamW",
    "Adamax",
    "Nadam",
    "LAMB",
    "LARS",
    "RMSProp",
    "AdaGrad",
    "AdaDelta",
    "FTRL",
    "SGLD",
    "DCASGD",
    "Test",
    "Updater",
    "get_updater",
    "create",
    "register",
]


@functools.lru_cache(maxsize=None)
def _jit_update(fn, static_hypers):
    """Jitted wrapper: dynamic (weight, grad, states, lr, wd, rescale_grad),
    static rest. rescale_grad must stay dynamic — AMP loss scaling folds a
    new value in per scale change and must not retrace."""
    hypers = dict(static_hypers)

    # donate weight + states (rebound after the call); grad is NOT donated —
    # grad_req='add' accumulators are read again by the next backward.
    # rescale_grad is a dynamic operand: AMP loss scaling and batch-size
    # changes fold into it every step and must not trigger a retrace.
    @functools.partial(jax.jit, donate_argnums=(0, 2))
    def step(weight, grad, states, lr, wd, rescale_grad):
        out = fn(weight, grad, *states, lr=lr, wd=wd,
                 rescale_grad=rescale_grad, **hypers)
        return out if isinstance(out, tuple) else (out,)

    return step


def _is_row_sparse(grad) -> bool:
    from ..ndarray.sparse import RowSparseNDArray

    return isinstance(grad, RowSparseNDArray) and grad._pair


def _rs_aggregate(grad, rescale, clip):
    """Compressed (rows, vals) -> (unique_rows, summed_vals, valid_mask).

    Duplicate rows (the same token appearing twice in a batch) must sum
    BEFORE clipping/decay — matching what the dense scatter-add would have
    produced. Output stays fixed-size (K slots, padded rows masked) so the
    path is jit-compatible."""
    rows, vals = grad._rs_rows, grad._rs_vals
    K = rows.shape[0]
    nrows = grad.shape[0]
    rows_u, inv = jnp.unique(rows, return_inverse=True, size=K,
                             fill_value=nrows)
    agg = jnp.zeros_like(vals).at[inv].add(vals)
    valid = rows_u < nrows
    rows_safe = jnp.where(valid, rows_u, 0)
    g = agg * rescale
    if clip is not None:
        g = jnp.clip(g, -clip, clip)
    return rows_safe, g, valid


class Optimizer:
    """Base optimizer. Reference API: create_state/update(+_multi_precision)."""

    opt_registry: Dict[str, type] = {}

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=None, lr_scheduler=None,
                 multi_precision=False, param_dict=None, aggregate_num=0):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate if learning_rate is not None else 0.01
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None and learning_rate is not None:
            lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.aggregate_num = aggregate_num
        self.begin_num_update = 0
        self.num_update = 0
        self._all_index_update_counts = {0: {}}
        self._index_update_count = self._all_index_update_counts[0]
        if param_idx2name is None:
            param_idx2name = {}
        if not isinstance(param_idx2name, dict):
            raise MXNetError("param_idx2name must be a dict of param indexes to names")
        self.idx2name = param_idx2name.copy()
        self.param_dict = param_dict if param_dict else {}
        self.lr_mult = {}
        self.wd_mult = {}

    # ------------------------------------------------------------- registry
    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        key = name.lower()
        if key not in Optimizer.opt_registry:
            raise MXNetError(f"cannot find optimizer {name!r}")
        return Optimizer.opt_registry[key](**kwargs)

    # ------------------------------------------------------------ state API
    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype == _np.float16:
            weight_master_copy = NDArray(weight.data.astype(jnp.float32))
            return (self.create_state(index, weight_master_copy), weight_master_copy)
        if weight.dtype == _np.float16 and not self.multi_precision:
            logging.warning(
                "Accumulating with float16 in optimizer can lead to poor accuracy "
                "or slow convergence. Consider using multi_precision=True."
            )
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):  # pragma: no cover - abstract
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype == _np.float16:
            inner_state, weight32 = state
            grad32 = NDArray(grad.data.astype(jnp.float32))
            self.update(index, weight32, grad32, inner_state)
            weight._rebind(weight32.data.astype(weight.data.dtype))
        else:
            self.update(index, weight, grad, state)

    # ------------------------------------------------------------ lr/wd mult
    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError("LRScheduler of the optimizer has already been defined")
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = args_lr_mult.copy()

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            is_weight = n.endswith("_weight")
            if not is_weight:
                self.wd_mult[n] = 0.0
        self.wd_mult.update(args_wd_mult)

    def _set_current_context(self, device_id):
        if device_id not in self._all_index_update_counts:
            self._all_index_update_counts[device_id] = {}
        self._index_update_count = self._all_index_update_counts[device_id]

    def _update_count(self, index):
        if not isinstance(index, (list, tuple)):
            index = [index]
        for idx in index:
            if idx not in self._index_update_count:
                self._index_update_count[idx] = self.begin_num_update
            self._index_update_count[idx] += 1
            self.num_update = max(self._index_update_count[idx], self.num_update)

    def _get_lrs(self, indices):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        lrs = [lr] * len(indices)
        for i, index in enumerate(indices):
            if index in self.param_dict:
                lrs[i] *= self.param_dict[index].lr_mult
            elif index in self.lr_mult:
                lrs[i] *= self.lr_mult[index]
            elif index in self.idx2name:
                lrs[i] *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lrs

    def _get_lr(self, index):
        return self._get_lrs([index])[0]

    def _get_wds(self, indices):
        wds = [self.wd] * len(indices)
        for i, index in enumerate(indices):
            if index in self.param_dict:
                wds[i] *= self.param_dict[index].wd_mult
            elif index in self.wd_mult:
                wds[i] *= self.wd_mult[index]
            elif index in self.idx2name:
                wds[i] *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wds

    def _get_wd(self, index):
        return self._get_wds([index])[0]

    # ------------------------------------------------------- fused dispatch
    def _apply(self, fn, weight, grad, states, lr, wd, **static_hypers):
        """Run a pure fused-update op and rebind weight/states in place."""
        # the update DONATES weight+state buffers; any queued eager op
        # that captured them must execute first or it reads deleted memory
        from ..imperative import flush_bulk

        flush_bulk()
        hypers = dict(static_hypers)
        rescale = float(hypers.pop("rescale_grad", self.rescale_grad))
        hypers.setdefault(
            "clip_gradient",
            float(self.clip_gradient) if self.clip_gradient is not None else -1.0,
        )
        step = _jit_update(fn, tuple(sorted(hypers.items())))
        state_list = [s for s in states if s is not None]
        outs = step(
            weight.data,
            grad.data,
            tuple(s.data for s in state_list),
            jnp.float32(lr),
            jnp.float32(wd),
            jnp.float32(rescale),
        )
        weight._rebind(outs[0])
        for s, new in zip(state_list, outs[1:]):
            s._rebind(new)

    def __getstate__(self):
        # param_dict holds live (unpicklable) Parameter objects; the loader
        # reattaches it (Trainer.load_states does) — reference behavior
        ret = self.__dict__.copy()
        ret["param_dict"] = {}
        return ret


register = Optimizer.register
create = Optimizer.create_optimizer


@register
class SGD(Optimizer):
    """SGD with momentum and optional multi-precision (reference
    ``sgd_update``/``sgd_mom_update``/``mp_sgd_*``)."""

    def __init__(self, momentum=0.0, lazy_update=True, learning_rate=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros(weight.shape, weight.data.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if _is_row_sparse(grad):
            if self.momentum:
                raise MXNetError(
                    "sparse SGD with momentum is not supported (the "
                    "reference's sparse sgd_mom kept dense momentum; use "
                    "momentum=0 for row_sparse grads)"
                )
            rows, g, valid = _rs_aggregate(grad, self.rescale_grad,
                                           self.clip_gradient)
            w = weight.data
            upd = lr * (g + wd * jnp.take(w, rows, axis=0))
            upd = upd * valid[:, None]
            weight._rebind(w.at[rows].add(-upd.astype(w.dtype)))
            return
        if state is None:
            self._apply(_fused.sgd_update, weight, grad, (), lr, wd)
        else:
            self._apply(_fused.sgd_mom_update, weight, grad, (state,), lr, wd,
                        momentum=self.momentum)


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros(weight.shape, weight.data.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if state is None:
            self._apply(_fused.signsgd_update, weight, grad, (), lr, wd)
        else:
            self._apply(_fused.signum_update, weight, grad, (state,), lr, wd,
                        momentum=self.momentum, wd_lh=self.wd_lh)


@register
class NAG(Optimizer):
    def __init__(self, momentum=0.0, learning_rate=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros(weight.shape, weight.data.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if state is None:
            self._apply(_fused.sgd_update, weight, grad, (), lr, wd)
        else:
            self._apply(_fused.nag_mom_update, weight, grad, (state,), lr, wd,
                        momentum=self.momentum)


@register
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (
            NDArray(jnp.zeros(weight.shape, weight.data.dtype)),  # mean
            NDArray(jnp.zeros(weight.shape, weight.data.dtype)),  # var
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        # bias correction folded into lr (reference does the same in Python)
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr = lr * math.sqrt(coef2) / coef1
        mean, var = state
        if _is_row_sparse(grad):
            # lazy adam (reference ``lazy_update=True``): moments and
            # weight rows touched only where the gradient has rows
            rows, g, valid = _rs_aggregate(grad, self.rescale_grad,
                                           self.clip_gradient)
            w = weight.data
            g = g + wd * jnp.take(w, rows, axis=0)
            m_old = jnp.take(mean.data, rows, axis=0)
            v_old = jnp.take(var.data, rows, axis=0)
            m_r = self.beta1 * m_old + (1.0 - self.beta1) * g
            v_r = self.beta2 * v_old + (1.0 - self.beta2) * g * g
            vm = valid[:, None]
            mean._rebind(mean.data.at[rows].add((m_r - m_old) * vm))
            var._rebind(var.data.at[rows].add((v_r - v_old) * vm))
            upd = lr * m_r / (jnp.sqrt(v_r) + self.epsilon) * vm
            weight._rebind(w.at[rows].add(-upd.astype(w.dtype)))
            return
        self._apply(_fused.adam_update, weight, grad, (mean, var), lr, wd,
                    beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon)


@register
class AdamW(Optimizer):
    """Adam with decoupled weight decay (reference contrib ``adamw_update``)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, correct_bias=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.correct_bias = correct_bias

    def create_state(self, index, weight):
        return (
            NDArray(jnp.zeros(weight.shape, weight.data.dtype)),
            NDArray(jnp.zeros(weight.shape, weight.data.dtype)),
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if self.correct_bias:
            t = self._index_update_count[index]
            lr = lr * math.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)
        mean, var = state
        self._apply(_fused.adamw_update, weight, grad, (mean, var), lr, wd,
                    beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon)


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (
            NDArray(jnp.zeros(weight.shape, weight.data.dtype)),
            NDArray(jnp.zeros(weight.shape, weight.data.dtype)),
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        lr /= 1.0 - self.beta1 ** t
        m, u = state
        g = grad.data * self.rescale_grad + wd * weight.data
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        new_m = self.beta1 * m.data + (1.0 - self.beta1) * g
        new_u = jnp.maximum(self.beta2 * u.data, jnp.abs(g))
        m._rebind(new_m)
        u._rebind(new_u)
        weight._rebind(weight.data - lr * new_m / new_u)


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        return (
            NDArray(jnp.zeros(weight.shape, weight.data.dtype)),
            NDArray(jnp.zeros(weight.shape, weight.data.dtype)),
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        g = grad.data * self.rescale_grad + wd * weight.data
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        momentum_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        m, v = state
        grad_prime = g / (1.0 - self.m_schedule)
        new_m = self.beta1 * m.data + (1.0 - self.beta1) * g
        new_v = self.beta2 * v.data + (1.0 - self.beta2) * jnp.square(g)
        m_t_prime = new_m / (1.0 - m_schedule_next)
        v_t_prime = new_v / (1.0 - self.beta2 ** t)
        m_t_bar = (1.0 - momentum_t) * grad_prime + momentum_t_1 * m_t_prime
        m._rebind(new_m)
        v._rebind(new_v)
        weight._rebind(
            weight.data - lr * m_t_bar / (jnp.sqrt(v_t_prime) + self.epsilon)
        )


@register
class LAMB(Optimizer):
    """Layer-wise adaptive large-batch optimizer (reference
    ``lamb_update_phase1/2`` in ``src/operator/optimizer_op.cc``)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (
            NDArray(jnp.zeros(weight.shape, weight.data.dtype)),
            NDArray(jnp.zeros(weight.shape, weight.data.dtype)),
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        mean, var = state
        g, new_mean, new_var = _fused.lamb_update_phase1(
            weight.data, grad.data, mean.data, var.data,
            beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon, t=t,
            bias_correction=self.bias_correction, wd=wd,
            rescale_grad=self.rescale_grad,
            clip_gradient=self.clip_gradient if self.clip_gradient is not None else -1.0,
        )
        mean._rebind(new_mean)
        var._rebind(new_var)
        r1 = jnp.linalg.norm(weight.data)
        r2 = jnp.linalg.norm(g)
        new_w = _fused.lamb_update_phase2(
            weight.data, g, r1, r2, lr=lr,
            lower_bound=self.lower_bound if self.lower_bound is not None else -1.0,
            upper_bound=self.upper_bound if self.upper_bound is not None else -1.0,
        )
        weight._rebind(new_w)


@register
class LARS(Optimizer):
    """Layer-wise adaptive rate scaling (reference ``lars_*`` ops)."""

    def __init__(self, learning_rate=0.1, momentum=0.0, eta=0.001,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.eta = eta
        self.epsilon = epsilon

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros(weight.shape, weight.data.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        w_norm = jnp.linalg.norm(weight.data)
        g = grad.data * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        g_norm = jnp.linalg.norm(g)
        trust = jnp.where(
            jnp.logical_and(w_norm > 0, g_norm > 0),
            self.eta * w_norm / (g_norm + wd * w_norm + self.epsilon),
            1.0,
        )
        g = g + wd * weight.data
        if state is not None:
            new_mom = self.momentum * state.data + lr * trust * g
            state._rebind(new_mom)
            weight._rebind(weight.data - new_mom)
        else:
            weight._rebind(weight.data - lr * trust * g)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (
                NDArray(jnp.zeros(weight.shape, weight.data.dtype)),  # n
                NDArray(jnp.zeros(weight.shape, weight.data.dtype)),  # g
                NDArray(jnp.zeros(weight.shape, weight.data.dtype)),  # delta
            )
        return (NDArray(jnp.zeros(weight.shape, weight.data.dtype)),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        cw = self.clip_weights if self.clip_weights is not None else -1.0
        if self.centered:
            n, g, delta = state
            self._apply(_fused.rmspropalex_update, weight, grad, (n, g, delta),
                        lr, wd, gamma1=self.gamma1, gamma2=self.gamma2,
                        epsilon=self.epsilon, clip_weights=cw)
        else:
            (n,) = state
            self._apply(_fused.rmsprop_update, weight, grad, (n,), lr, wd,
                        gamma1=self.gamma1, epsilon=self.epsilon, clip_weights=cw)


@register
class AdaGrad(Optimizer):
    def __init__(self, learning_rate=None, eps=1e-7, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return NDArray(jnp.zeros(weight.shape, weight.data.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad.data * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        g = g + wd * weight.data
        new_h = state.data + jnp.square(g)
        state._rebind(new_h)
        weight._rebind(
            weight.data - lr * g / (jnp.sqrt(new_h) + self.float_stable_eps)
        )


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (
            NDArray(jnp.zeros(weight.shape, weight.data.dtype)),  # E[g^2]
            NDArray(jnp.zeros(weight.shape, weight.data.dtype)),  # E[dx^2]
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        g = grad.data * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        g = g + wd * weight.data
        acc_g, acc_delta = state
        new_acc_g = self.rho * acc_g.data + (1.0 - self.rho) * jnp.square(g)
        delta = (
            jnp.sqrt(acc_delta.data + self.epsilon)
            / jnp.sqrt(new_acc_g + self.epsilon)
        ) * g
        new_acc_delta = self.rho * acc_delta.data + (1.0 - self.rho) * jnp.square(delta)
        acc_g._rebind(new_acc_g)
        acc_delta._rebind(new_acc_delta)
        weight._rebind(weight.data - delta)


@register
class FTRL(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (
            NDArray(jnp.zeros(weight.shape, weight.data.dtype)),  # z
            NDArray(jnp.zeros(weight.shape, weight.data.dtype)),  # n
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        z, n = state
        self._apply(_fused.ftrl_update, weight, grad, (z, n), lr, wd,
                    lamda1=self.lamda1, beta=self.beta)


@register
class FTML(Optimizer):
    """Follow the Moving Leader (reference ``FTML`` optimizer over
    ``ftml_update`` [unverified]; Zheng & Kwok 2017)."""

    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8,
                 learning_rate=0.0025, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (
            NDArray(jnp.zeros(weight.shape, weight.data.dtype)),  # d
            NDArray(jnp.zeros(weight.shape, weight.data.dtype)),  # v
            NDArray(jnp.zeros(weight.shape, weight.data.dtype)),  # z
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        d, v, z = state
        # inline (AdaGrad-style) rather than _apply: t changes per step
        # and would retrace a static-hyper jit every call
        nw, nd_, nv, nz = _fused.ftml_update(
            weight.data, grad.data, d.data, v.data, z.data, lr=lr,
            beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
            t=self._index_update_count[index], wd=wd,
            rescale_grad=self.rescale_grad,
            clip_grad=self.clip_gradient
            if self.clip_gradient is not None else -1.0)
        weight._rebind(nw)
        d._rebind(nd_)
        v._rebind(nv)
        z._rebind(nz)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics."""

    def __init__(self, learning_rate=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)

    def update(self, index, weight, grad, state):
        from .. import random as _random

        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad.data * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        g = g + wd * weight.data
        noise = jax.random.normal(_random.next_key(), weight.shape,
                                  weight.data.dtype) * math.sqrt(lr)
        weight._rebind(weight.data - lr / 2 * g + noise)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference ``dcasgd``)."""

    def __init__(self, momentum=0.0, lamda=0.04, learning_rate=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, NDArray(jnp.array(weight.data)))
        return (
            NDArray(jnp.zeros(weight.shape, weight.data.dtype)),
            NDArray(jnp.array(weight.data)),
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad.data * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        mon, previous_weight = state
        comp = g + wd * weight.data + self.lamda * g * g * (
            weight.data - previous_weight.data
        )
        if mon is not None:
            new_mon = self.momentum * mon.data - lr * comp
            mon._rebind(new_mon)
            delta = new_mon
        else:
            delta = -lr * comp
        previous_weight._rebind(weight.data)
        weight._rebind(weight.data + delta)


@register
class Test(Optimizer):
    """Reference test optimizer: w -= lr * grad (no wd)."""

    def __init__(self, learning_rate=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)

    def create_state(self, index, weight):
        return NDArray(jnp.zeros(weight.shape, weight.data.dtype))

    def update(self, index, weight, grad, state):
        weight._rebind(weight.data - self.lr * grad.data * self.rescale_grad)


ccSGD = SGD  # reference back-compat alias


class Updater:
    """Stateful update closure used by KVStore servers (reference
    ``get_updater`` / ``Updater`` in optimizer.py)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}
        self.aggregate_updates = optimizer.aggregate_num > 0

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(
                index, weight
            )
            self.states_synced[index] = True
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def get_states(self, dump_optimizer=False):
        states = (self.states, self.optimizer) if dump_optimizer else self.states

        def _to_np(x):
            if isinstance(x, NDArray):
                return x.asnumpy()
            if isinstance(x, (tuple, list)):
                return tuple(_to_np(i) for i in x)
            return x

        serialized = {k: _to_np(v) for k, v in self.states.items()}
        if dump_optimizer:
            return pickle.dumps((serialized, self.optimizer))
        return pickle.dumps(serialized)

    def set_states(self, states):
        loaded = pickle.loads(states)
        if isinstance(loaded, tuple) and len(loaded) == 2 and isinstance(
            loaded[1], Optimizer
        ):
            loaded, self.optimizer = loaded

        def _to_nd(x):
            if isinstance(x, _np.ndarray):
                return NDArray(jnp.asarray(x))
            if isinstance(x, (tuple, list)):
                return tuple(_to_nd(i) for i in x)
            return x

        self.states = {k: _to_nd(v) for k, v in loaded.items()}
        self.states_synced = dict.fromkeys(self.states.keys(), False)


def get_updater(optimizer: Optimizer) -> Updater:
    return Updater(optimizer)
