"""Optimizers (reference: ``python/mxnet/optimizer/`` [unverified])."""

from . import optimizer
from .optimizer import *  # noqa: F401,F403
from . import lr_scheduler
from .lr_scheduler import LRScheduler  # noqa: F401

__all__ = optimizer.__all__ + ["lr_scheduler", "LRScheduler"]
