// Native RecordIO reader + JPEG decoder for the data-pipeline hot path.
//
// TPU-native analogue of the reference's C++ IO stack
// (dmlc-core RecordIOReader + src/io ImageRecordIOParser2 [unverified]):
// the Python layer (mxnet_tpu/recordio.py) owns the format and the write
// path; this library accelerates the read path — framing scan, indexed
// record fetch, and libjpeg decode — which dominates input-bound training.
//
// Wire format (identical to mxnet_tpu/recordio.py):
//   [u32 magic=0xced7230a][u32 lrec = cflag<<29 | len][len bytes][pad to 4]
//   cflag: 0 whole record, 1 first chunk, 2 middle, 3 last.
//
// Build: g++ -O2 -shared -fPIC -o libmxtpu_io.so librecordio.cc -ljpeg
// (mxnet_tpu/_native.py compiles this on demand and caches the .so).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

#include <jpeglib.h>
#include <csetjmp>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

struct Record {
  int64_t offset;  // file offset of the first chunk header
  int64_t size;    // total payload bytes (chunks joined)
  int64_t end;     // file offset just past the record (incl. padding)
};

struct Reader {
  FILE* f = nullptr;
  std::vector<Record> records;
};

// reads the chunked record starting at `off`; returns payload size or -1.
// If out != nullptr, copies payload (caller guarantees capacity).
int64_t read_record_at(FILE* f, int64_t off, char* out, int64_t cap) {
  if (fseeko(f, off, SEEK_SET) != 0) return -1;
  int64_t total = 0;
  for (;;) {
    uint32_t head[2];
    if (fread(head, 4, 2, f) != 2) return total > 0 ? -1 : -1;
    if (head[0] != kMagic) return -1;
    uint32_t cflag = head[1] >> 29;
    uint32_t len = head[1] & kLenMask;
    if (out != nullptr) {
      if (total + (int64_t)len > cap) return -1;
      if (len && fread(out + total, 1, len, f) != len) return -1;
    } else {
      if (len && fseeko(f, len, SEEK_CUR) != 0) return -1;
    }
    uint32_t pad = (4 - (len % 4)) % 4;
    if (pad && fseeko(f, pad, SEEK_CUR) != 0) return -1;
    total += len;
    if (cflag == 0 || cflag == 3) return total;
  }
}

}  // namespace

extern "C" {

int mxtpu_io_abi_version() { return 1; }

// Open a .rec file and scan the full framing into an offset index.
void* mxtpu_rio_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  Reader* r = new Reader();
  r->f = f;
  int64_t off = 0;
  for (;;) {
    uint32_t head[2];
    if (fseeko(f, off, SEEK_SET) != 0) break;
    if (fread(head, 4, 2, f) != 2) break;  // EOF
    if (head[0] != kMagic) {  // corrupt tail
      delete r;
      fclose(f);
      return nullptr;
    }
    // walk chunks of this record to find its end
    int64_t size = read_record_at(f, off, nullptr, 0);
    if (size < 0) break;
    int64_t end;
#ifdef _WIN32
    end = ftell(f);
#else
    end = ftello(f);
#endif
    r->records.push_back({off, size, end});
    off = end;
  }
  return r;
}

long long mxtpu_rio_count(void* h) {
  return h ? (long long)static_cast<Reader*>(h)->records.size() : 0;
}

long long mxtpu_rio_size(void* h, long long i) {
  Reader* r = static_cast<Reader*>(h);
  if (!r || i < 0 || (size_t)i >= r->records.size()) return -1;
  return r->records[i].size;
}

long long mxtpu_rio_offset(void* h, long long i) {
  Reader* r = static_cast<Reader*>(h);
  if (!r || i < 0 || (size_t)i >= r->records.size()) return -1;
  return r->records[i].offset;
}

long long mxtpu_rio_end(void* h, long long i) {
  Reader* r = static_cast<Reader*>(h);
  if (!r || i < 0 || (size_t)i >= r->records.size()) return -1;
  return r->records[i].end;
}

// Read record i into buf (cap bytes); returns bytes written or -1.
long long mxtpu_rio_read(void* h, long long i, char* buf, long long cap) {
  Reader* r = static_cast<Reader*>(h);
  if (!r || i < 0 || (size_t)i >= r->records.size()) return -1;
  return read_record_at(r->f, r->records[i].offset, buf, cap);
}

// Read the record that starts at a raw file offset (for .idx lookups).
long long mxtpu_rio_read_at(void* h, long long offset, char* buf,
                            long long cap) {
  Reader* r = static_cast<Reader*>(h);
  if (!r) return -1;
  return read_record_at(r->f, offset, buf, cap);
}

void mxtpu_rio_close(void* h) {
  Reader* r = static_cast<Reader*>(h);
  if (!r) return;
  if (r->f) fclose(r->f);
  delete r;
}

// ------------------------------------------------------------------- JPEG
struct JpegErr {
  jpeg_error_mgr pub;
  jmp_buf jb;
};

static void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* err = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(err->jb, 1);
}

// Probe dims: returns 0 on success, fills w/h/channels (channels forced 3).
int mxtpu_jpeg_probe(const unsigned char* buf, long long len, int* w, int* h,
                     int* c) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf, (unsigned long)len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  *w = cinfo.image_width;
  *h = cinfo.image_height;
  *c = 3;
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Decode to HWC uint8 BGR (cv2 wire convention used by the Python layer).
// Returns 0 on success.
int mxtpu_jpeg_decode(const unsigned char* buf, long long len,
                      unsigned char* out, long long cap) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf, (unsigned long)len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  const int w = cinfo.output_width, hgt = cinfo.output_height;
  const int stride = w * 3;
  if ((long long)stride * hgt > cap) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  std::vector<unsigned char> row(stride);
  unsigned char* rp = row.data();
  while (cinfo.output_scanline < cinfo.output_height) {
    int y = cinfo.output_scanline;
    jpeg_read_scanlines(&cinfo, &rp, 1);
    unsigned char* dst = out + (int64_t)y * stride;
    for (int x = 0; x < w; ++x) {  // RGB -> BGR
      dst[x * 3 + 0] = rp[x * 3 + 2];
      dst[x * 3 + 1] = rp[x * 3 + 1];
      dst[x * 3 + 2] = rp[x * 3 + 0];
    }
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

}  // extern "C"
